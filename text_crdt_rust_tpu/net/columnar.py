"""Columnar TXNS wire format (frame version 2) — the automerge gear.

The row codec (``net/codec.py``, frame version 1) spends most of its
bytes on per-op structure: every txn repeats ids, parents, origins and
lengths inline, so a single-char edit costs ~15-20 wire bytes.  The
automerge binary document format (PAPERS.md) shows the next gear: strip
the structure out into **columns**, delta-code each column against a
cheap predictor, and run-length-encode the residuals — whole columns of
"the obvious value" collapse to a few bytes, and what remains is close
to the information actually carried.

Frame layout (outer framing identical to v1 — same MAGIC, varint
length, trailing CRC32C over *everything* before it — only the version
byte differs, which is how old row frames keep decoding side by side):

``frame := MAGIC(1B) VERSION=2(1B) varint(payload_len) payload CRC32C``
``payload := kind(1B) flags(1B) body``
``body(TXNS) := names varint(n_txns) varint(n_chunks) chunk*``
``body(TXNS_MUX) := docnames names varint(n_txns) varint(n_chunks) chunk*``
``chunk := (col_id << 1 | enc)(1B) varint(byte_len) bytes``

``flags`` bit 0 set means the body (everything after the flags byte)
is one DEFLATE stream prefixed by ``varint(raw_len)`` — the automerge
compressed-chunk trick lifted to the whole frame, which is what makes
the per-frame name tables (hundreds of ``d0123.a0``-shaped agent names
on a multiplexed connection) nearly free.

The **TXNS_MUX** body is the connection-level multiplexed form: one
frame carries many documents' txn batches, each txn tagged by a
``DOC`` column index into a doc-id string table.  Per-doc frames pay
the fixed frame + name-table + chunk-header cost per *document*; a
replication link (edge aggregator, shard-to-shard migration) pays it
once per *window* — on the 200-doc loadgen this is the difference
between a ~3x and a >5x bytes-per-op cut, because the Zipf cold tail
is all overhead.

A chunk's ``bytes`` (after undoing ``enc``: 0 = raw, 1 = DEFLATE — the
encoder picks whichever is smaller, per chunk) are RLE runs over
zigzag-LEB128 **residuals**:

``runs := { varint(run_len) varint(zigzag(residual)) }*``

and each column's residual is its value minus a *predictor* the decoder
can reconstruct: the PER-AGENT seq chain for ``T_SEQ`` (an agent's next
txn seq is its last seq + length — a linear history collapses to one
run of zeros), ``author`` for parent/origin agent indices, the parent
agent's own previous txn seq for parent seqs (a linear continuation or
a just-carried merge point costs ~0), the txn's own emission cursor
``seq + chars_emitted - 1`` for an origin-left on the author's OWN
chain (a typing run is all zeros), previous-value chains for foreign
origin-lefts and all origin-rights (a run typed into existing text
keeps one successor char), the previous delete's ``seq+len`` for delete
targets (a sweep chains), and the ROOT sentinel seq wherever the
origin's *agent* already says ROOT (tail appends would otherwise pay a
5-byte varint each).  Insert content rides as one concatenated
codepoint column.  Count-like columns predict their modal value
(1 parent, 1 op) as the chain seed.
A column whose residuals are ALL ZERO — every value perfectly
predicted, the common case for whole columns of a single-agent frame —
is simply absent, as is an empty one: the decoder reconstructs an
absent column as pure prediction.

Hard-rejection contract (PR 1, kept bit for bit): the outer CRC32C
covers every chunk, so ANY corruption — including truncation mid-column
-chunk — is a typed ``CodecError``; on top of that the body is
structurally validated (runs must land exactly on the expected count,
indices/seqs/lengths are range-checked, every decoded txn passes
``validate_remote_txn``) so even a hand-built CRC-valid frame can never
mis-decode.  DEFLATE — per chunk and per frame body — is inflated
through a bounded decompressor (a declared column/body can never expand
past its declared size), so adversarial frames cannot balloon memory.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Sequence, Tuple, Union

from ..common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    validate_remote_txn,
)
from .codec import (
    KIND_TXNS,
    KIND_TXNS_MUX,
    CodecError,
    _collect_names,
    _frame,
    _read_names,
    _read_varint,
    _write_names,
    _write_varint,
)

FRAME_VERSION_COLUMNAR = 2

# Column ids.  The decoder walks them in dependency order (counts before
# the columns they size, ops before the txn-seq chain that needs txn
# lengths), so the ids are a namespace, not a decode order.
T_AGENT, T_SEQ, T_NPAR, T_NOPS = 0, 1, 2, 3
P_AGENT, P_SEQ = 4, 5
OP_TAG = 6
I_OLA, I_OLS, I_ORA, I_ORS, I_LEN, CONTENT = 7, 8, 9, 10, 11, 12
D_AGENT, D_SEQ, D_LEN = 13, 14, 15
DOC = 16   # TXNS_MUX only: per-txn doc-table index

_COLS_TXNS = frozenset(range(16))
_COLS_MUX = frozenset(range(17))

ENC_RAW = 0
ENC_DEFLATE = 1

_FLAG_DEFLATE = 1  # payload flags bit 0: body is one DEFLATE stream

_U32_MAX = 0xFFFF_FFFF
# Decode-side memory bounds.  RLE means a tiny frame can legitimately
# declare many values (that is the point), so counts cannot be bounded
# by payload length the way the row codec bounds them — these caps are
# the adversarial-allocation ceiling instead.  Encoders chunk:
# ``encode_txns_stream``/``encode_mux_stream`` emit back-to-back frames
# under the caps.
_MAX_TXNS = 1 << 16          # txns per frame
_MAX_DOCS = 1 << 14          # doc table entries per mux frame
_MAX_PARENTS = 1 << 18       # total parents per frame
_MAX_OPS = 1 << 18           # total ops per frame
_MAX_CONTENT = 1 << 20       # total insert codepoints per frame
_MAX_BODY = 1 << 23          # declared raw size of a deflated body
# Only deflate chunks big enough to plausibly win (DEFLATE costs ~11
# bytes of fixed overhead before any gain).
_DEFLATE_MIN = 64


# -- zigzag ------------------------------------------------------------------

def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


# -- run codec ---------------------------------------------------------------

def _enc_runs(residuals: Sequence[int]) -> bytes:
    """RLE runs of zigzag-LEB128 residuals; no count header — the
    decoder knows every column's exact expected length."""
    out = bytearray()
    i, n = 0, len(residuals)
    while i < n:
        v = residuals[i]
        j = i + 1
        while j < n and residuals[j] == v:
            j += 1
        _write_varint(out, j - i)
        _write_varint(out, _zigzag(v))
        i = j
    return bytes(out)


def _dec_runs(buf: bytes, expect_n: int, what: str) -> List[int]:
    """Inverse of ``_enc_runs``: must land EXACTLY on ``expect_n``
    residuals and consume the whole buffer."""
    out: List[int] = []
    cur, end = 0, len(buf)
    while cur < end:
        run, cur = _read_varint(buf, cur, end)
        if run < 1 or len(out) + run > expect_n:
            raise CodecError(
                f"{what} column overruns expected {expect_n} values")
        zz, cur = _read_varint(buf, cur, end)
        out.extend([_unzigzag(zz)] * run)
    if len(out) != expect_n:
        raise CodecError(
            f"{what} column holds {len(out)} values, expected {expect_n}")
    return out


# -- chunk layer -------------------------------------------------------------

def _write_chunk(out: bytearray, col_id: int, raw: bytes) -> None:
    enc, body = ENC_RAW, raw
    if len(raw) >= _DEFLATE_MIN:
        packed = zlib.compress(raw, 9)
        if len(packed) < len(raw):
            enc, body = ENC_DEFLATE, packed
    out.append((col_id << 1) | enc)
    _write_varint(out, len(body))
    out += body


def _read_chunks(buf: bytes, cur: int, end: int, known: frozenset
                 ) -> Tuple[Dict[int, Tuple[int, bytes]], int]:
    count, cur = _read_varint(buf, cur, end)
    if count > end - cur:  # each chunk costs >= 2 bytes
        raise CodecError("chunk count longer than payload")
    chunks: Dict[int, Tuple[int, bytes]] = {}
    for _ in range(count):
        if cur >= end:
            raise CodecError("truncated chunk header")
        col_id, enc = buf[cur] >> 1, buf[cur] & 1
        cur += 1
        if col_id not in known:
            raise CodecError(f"unknown column id {col_id}")
        if col_id in chunks:
            raise CodecError(f"duplicate column id {col_id}")
        ln, cur = _read_varint(buf, cur, end)
        if ln > end - cur:
            raise CodecError("truncated column chunk")
        chunks[col_id] = (enc, buf[cur:cur + ln])
        cur += ln
    return chunks, cur


def _col(chunks: Dict[int, Tuple[int, bytes]], col_id: int, expect_n: int,
         what: str) -> List[int]:
    """Decode one column to residuals; an absent chunk is all-zero
    residuals (every value predicted exactly — the encoder elides it)."""
    got = chunks.get(col_id)
    if got is None:
        return [0] * expect_n
    enc, body = got
    if enc == ENC_DEFLATE:
        # Bounded inflate: a column of expect_n residuals can never
        # legitimately exceed ~11 bytes per value (two max varints).
        cap = 22 * max(expect_n, 1) + 64
        body = _bounded_inflate(body, cap, what)
    return _dec_runs(body, expect_n, what)


def _bounded_inflate(data: bytes, cap: int, what: str) -> bytes:
    d = zlib.decompressobj()
    try:
        out = d.decompress(data, cap)
    except zlib.error as e:
        raise CodecError(f"{what} inflate failed: {e}") from None
    if d.unconsumed_tail or not d.eof or d.unused_data:
        raise CodecError(f"{what} exceeds inflate bound or carries "
                         f"trailing garbage")
    return out


# -- encode ------------------------------------------------------------------

def _encode_cols(pairs: Sequence[Tuple[int, RemoteTxn]], aidx: Dict[str, int],
                 mux: bool) -> List[Tuple[int, List[int]]]:
    """Residual columns for a flattened ``(doc_idx, txn)`` stream (the
    single-doc body is the degenerate ``doc_idx == 0`` case with the
    DOC column omitted)."""
    doc_col: List[int] = []
    t_agent: List[int] = []
    t_seq: List[int] = []
    t_npar: List[int] = []
    t_nops: List[int] = []
    p_agent: List[int] = []
    p_seq: List[int] = []
    op_tag: List[int] = []
    i_ola: List[int] = []
    i_ols: List[int] = []
    i_ora: List[int] = []
    i_ors: List[int] = []
    i_len: List[int] = []
    content: List[int] = []
    d_agent: List[int] = []
    d_seq: List[int] = []
    d_len: List[int] = []

    chain: Dict[int, int] = {}  # author idx -> its last txn's seq + len
    last_seq: Dict[int, int] = {}  # author idx -> its last txn's seq
    # Chain seeds: counts start at their modal value (one parent, one
    # op), so the typical column is all-zero residuals and elided.
    prev = dict(doc=0, t_agent=0, t_npar=1, t_nops=1, op_tag=0,
                i_ols=0, i_ors=0, i_len=1, content=0, d_agent=0, d_len=1)
    d_chain = 0                 # previous delete's target seq + len

    def delta(key: str, v: int) -> int:
        r = v - prev[key]
        prev[key] = v
        return r

    for doc_i, txn in pairs:
        doc_col.append(delta("doc", doc_i))
        author = aidx[txn.id.agent]
        seq = txn.id.seq
        t_agent.append(delta("t_agent", author))
        t_seq.append(seq - chain.get(author, 0))
        t_npar.append(delta("t_npar", len(txn.parents)))
        t_nops.append(delta("t_nops", len(txn.ops)))
        for p in txn.parents:
            # Parent agent rides author-relative; parent seq predicts
            # the PARENT AGENT's previous txn in this stream (a linear
            # continuation — own or a merge point on a peer we just
            # carried — costs ~0), falling back to seq - 1.
            p_idx = aidx[p.agent]
            p_agent.append(p_idx - author)
            p_seq.append(p.seq - last_seq.get(p_idx, seq - 1))
        tlen = 0
        emitted = 0             # insert chars already emitted this txn
        for op in txn.ops:
            if isinstance(op, RemoteIns):
                op_tag.append(delta("op_tag", 0))
                ola = aidx[op.origin_left.agent]
                ols = op.origin_left.seq
                # Origin-left agent rides author-relative (an author
                # extending their own run — the typing shape — is 0).
                i_ola.append(ola - author)
                if op.origin_left.agent == "ROOT":
                    i_ols.append(ols - _U32_MAX)
                elif ola == author:
                    # Own-chain origin: the char this txn's content is
                    # extending — exactly seq + emitted - 1 for a
                    # continuation, so a typing run is all zeros.
                    i_ols.append(ols - (seq + emitted - 1))
                else:
                    i_ols.append(ols - prev["i_ols"])
                    prev["i_ols"] = ols
                i_ora.append(aidx[op.origin_right.agent] - ola)
                if op.origin_right.agent == "ROOT":
                    i_ors.append(op.origin_right.seq - _U32_MAX)
                else:
                    # Previous-value chain: a typing run into existing
                    # text keeps ONE successor char for the whole run.
                    i_ors.append(op.origin_right.seq - prev["i_ors"])
                    prev["i_ors"] = op.origin_right.seq
                n = len(op.ins_content)
                i_len.append(delta("i_len", n))
                for ch in op.ins_content:
                    cp = ord(ch)
                    if 0xD800 <= cp <= 0xDFFF:
                        raise CodecError(
                            "insert content carries a lone surrogate")
                    content.append(delta("content", cp))
                tlen += n
                emitted += n
            else:
                op_tag.append(delta("op_tag", 1))
                d_agent.append(delta("d_agent", aidx[op.id.agent]))
                d_seq.append(op.id.seq - d_chain)
                d_chain = op.id.seq + op.len
                d_len.append(delta("d_len", op.len))
                tlen += op.len
        last_seq[author] = seq
        chain[author] = seq + tlen

    cols = [
        (T_AGENT, t_agent), (T_SEQ, t_seq), (T_NPAR, t_npar),
        (T_NOPS, t_nops), (P_AGENT, p_agent), (P_SEQ, p_seq),
        (OP_TAG, op_tag), (I_OLA, i_ola), (I_OLS, i_ols), (I_ORA, i_ora),
        (I_ORS, i_ors), (I_LEN, i_len), (CONTENT, content),
        (D_AGENT, d_agent), (D_SEQ, d_seq), (D_LEN, d_len),
    ]
    if mux:
        cols.insert(0, (DOC, doc_col))
    return cols


def _frame_budget(txns: Sequence[RemoteTxn], what: str) -> None:
    """Encode-side twin of the decoder's allocation caps: a frame that
    exceeds them would encode fine and then be rejected by EVERY
    compliant decoder — fail fast at the source (the stream encoders
    split windows under these budgets instead)."""
    if len(txns) > _MAX_TXNS:
        raise CodecError(
            f"{len(txns)} txns exceed per-frame cap {_MAX_TXNS} ({what})")
    n_ops = sum(len(t.ops) for t in txns)
    if n_ops > _MAX_OPS:
        raise CodecError(
            f"{n_ops} ops exceed per-frame cap {_MAX_OPS} ({what})")
    n_par = sum(len(t.parents) for t in txns)
    if n_par > _MAX_PARENTS:
        raise CodecError(
            f"{n_par} parents exceed per-frame cap {_MAX_PARENTS} ({what})")
    n_cp = sum(len(op.ins_content) for t in txns for op in t.ops
               if isinstance(op, RemoteIns))
    if n_cp > _MAX_CONTENT:
        raise CodecError(
            f"{n_cp} content codepoints exceed per-frame cap "
            f"{_MAX_CONTENT} ({what})")


def _txn_budget_cost(txn: RemoteTxn) -> Tuple[int, int, int]:
    """(ops, parents, codepoints) a txn spends against the frame caps."""
    return (len(txn.ops), len(txn.parents),
            sum(len(op.ins_content) for op in txn.ops
                if isinstance(op, RemoteIns)))


def _budget_windows(txns: Sequence, per_frame: int, cost):
    """Greedy split of a batch into windows each under the decode caps
    (``cost`` maps an item to its (ops, parents, codepoints) spend).
    A single item over the caps raises — it could never decode."""
    window: List = []
    ops = par = cp = 0
    for item in txns:
        o, p, c = cost(item)
        if window and (len(window) >= per_frame or ops + o > _MAX_OPS
                       or par + p > _MAX_PARENTS or cp + c > _MAX_CONTENT):
            yield window
            window, ops, par, cp = [], 0, 0, 0
        window.append(item)
        ops += o
        par += p
        cp += c
    if window:
        yield window


def _finish_frame(kind: int, raw_body: bytes) -> bytes:
    """Wrap a built body as one v2 frame, body-deflating when it wins
    (this is what makes multiplexed name tables nearly free). Bodies
    past 64 KiB skip the attempt: their chunks already deflated
    individually, so the whole-body pass is a near-certain loss paid
    in CPU on the biggest frames."""
    payload = bytearray([kind])
    if _DEFLATE_MIN <= len(raw_body) <= (1 << 16):
        packed = zlib.compress(raw_body, 9)
        header = bytearray()
        _write_varint(header, len(raw_body))
        if 1 + len(header) + len(packed) < 1 + len(raw_body):
            payload.append(_FLAG_DEFLATE)
            payload += header
            payload += packed
            return _frame(bytes(payload), version=FRAME_VERSION_COLUMNAR)
    payload.append(0)
    payload += raw_body
    return _frame(bytes(payload), version=FRAME_VERSION_COLUMNAR)


def encode_txns(txns: Sequence[RemoteTxn]) -> bytes:
    """One columnar (version 2) frame carrying a ``RemoteTxn`` batch.

    Decodes back — through ``codec.decode_frame``'s version negotiation
    — to exactly the structures ``codec.encode_txns`` would round-trip;
    the two formats are interchangeable on the wire.
    """
    for txn in txns:
        validate_remote_txn(txn)
    _frame_budget(txns, "encode_txns")
    table = _collect_names(txns)
    cols = _encode_cols([(0, t) for t in txns], table._ids, mux=False)
    body = bytearray()
    _write_names(body, table.names)
    _write_varint(body, len(txns))
    present = [(cid, res) for cid, res in cols if any(res)]
    _write_varint(body, len(present))
    for cid, res in present:
        _write_chunk(body, cid, _enc_runs(res))
    return _finish_frame(KIND_TXNS, bytes(body))


def encode_txns_stream(txns: Sequence[RemoteTxn],
                       per_frame: int = 4096) -> bytes:
    """Back-to-back columnar frames (``codec.decode_frames`` layout),
    windowed under ALL the decoder's adversarial-allocation caps (txn
    count, ops, parents, content) — the encoding for unbounded batches
    (anti-entropy resends, checkpoint deltas). A single txn over the
    caps raises: no framing could ever decode it."""
    if not txns:
        return encode_txns([])
    out = bytearray()
    for window in _budget_windows(txns, per_frame, _txn_budget_cost):
        out += encode_txns(window)
    return bytes(out)


def encode_mux(batches: Sequence[Tuple[str, Sequence[RemoteTxn]]]) -> bytes:
    """One TXNS_MUX frame: many documents' txn batches on one
    connection.  Per-doc txn order is preserved (that is the causal
    contract); doc interleaving is free — the DOC column is delta-coded
    so doc-sorted input costs ~2 bytes per document."""
    pairs: List[Tuple[int, RemoteTxn]] = []
    doc_ids: List[str] = []
    doc_idx: Dict[str, int] = {}
    for doc_id, txns in batches:
        i = doc_idx.get(doc_id)
        if i is None:
            i = doc_idx[doc_id] = len(doc_ids)
            doc_ids.append(doc_id)
        for txn in txns:
            validate_remote_txn(txn)
            pairs.append((i, txn))
    if len(doc_ids) > _MAX_DOCS:
        raise CodecError(f"{len(doc_ids)} docs exceed per-frame cap "
                         f"{_MAX_DOCS}")
    _frame_budget([t for _, t in pairs], "encode_mux")
    table = _collect_names([t for _, t in pairs])
    cols = _encode_cols(pairs, table._ids, mux=True)
    body = bytearray()
    _write_names(body, doc_ids)
    _write_names(body, table.names)
    _write_varint(body, len(pairs))
    present = [(cid, res) for cid, res in cols if any(res)]
    _write_varint(body, len(present))
    for cid, res in present:
        _write_chunk(body, cid, _enc_runs(res))
    return _finish_frame(KIND_TXNS_MUX, bytes(body))


def group_consecutive(pairs: Sequence[Tuple[str, RemoteTxn]]
                      ) -> List[Tuple[str, List[RemoteTxn]]]:
    """Fold a flat ``(doc_id, txn)`` stream into consecutive same-doc
    groups, order-preserving — the one grouping rule the mux encoder,
    stream splitter, and decoder all share."""
    grouped: List[Tuple[str, List[RemoteTxn]]] = []
    for doc_id, txn in pairs:
        if grouped and grouped[-1][0] == doc_id:
            grouped[-1][1].append(txn)
        else:
            grouped.append((doc_id, [txn]))
    return grouped


def encode_mux_stream(batches: Sequence[Tuple[str, Sequence[RemoteTxn]]],
                      per_frame: int = 4096) -> bytes:
    """Back-to-back TXNS_MUX frames chunked under the decode caps; a
    doc's batch may split across frames (per-doc txn order holds)."""
    flat: List[Tuple[str, RemoteTxn]] = [
        (doc_id, txn) for doc_id, txns in batches for txn in txns]
    if not flat:
        return encode_mux([])
    # A window of N txns references at most N docs, so capping the
    # window size at _MAX_DOCS keeps the doc table under its decode
    # cap too (callers may pass any per_frame).
    per_frame = min(per_frame, _MAX_DOCS)
    out = bytearray()
    for window in _budget_windows(flat, per_frame,
                                  lambda p: _txn_budget_cost(p[1])):
        out += encode_mux(group_consecutive(window))
    return bytes(out)


# -- decode ------------------------------------------------------------------

def _undelta(residuals: List[int], what: str, base: int = 0,
             lo: int = 0, hi: int = _U32_MAX) -> List[int]:
    """Previous-value predictor + range check (the single hardening
    point for every prev-coded column)."""
    out: List[int] = []
    v = base
    for r in residuals:
        v += r
        if v < lo or v > hi:
            raise CodecError(f"{what} value {v} out of range [{lo}, {hi}]")
        out.append(v)
    return out


def _unwrap_body(buf: bytes, cur: int, end: int
                 ) -> Tuple[bytes, int, int]:
    """Consume the flags byte; bounded-inflate the body when flagged.
    Returns ``(buffer, cur, end)`` to parse the raw body from."""
    if cur >= end:
        raise CodecError("truncated payload: missing flags byte")
    flags = buf[cur]
    cur += 1
    if flags & ~_FLAG_DEFLATE:
        raise CodecError(f"unknown payload flags {flags:#04x}")
    if not flags & _FLAG_DEFLATE:
        return buf, cur, end
    raw_len, cur = _read_varint(buf, cur, end)
    if raw_len > _MAX_BODY:
        raise CodecError(f"deflated body declares {raw_len} raw bytes, "
                         f"cap {_MAX_BODY}")
    body = _bounded_inflate(bytes(buf[cur:end]), raw_len, "frame body")
    if len(body) != raw_len:
        raise CodecError(f"deflated body inflated to {len(body)} bytes, "
                         f"declared {raw_len}")
    return body, 0, raw_len


def _decode_txn_cols(chunks: Dict[int, Tuple[int, bytes]],
                     names: List[str], n_txns: int) -> List[RemoteTxn]:
    """Reconstruct the txn stream from decoded column chunks (everything
    after the name tables and count header; shared by both bodies)."""
    n_names = len(names)

    t_agent = _undelta(_col(chunks, T_AGENT, n_txns, "txn agent"),
                       "txn agent index", hi=n_names - 1 if n_names else 0)
    t_npar = _undelta(_col(chunks, T_NPAR, n_txns, "parent count"),
                      "parent count", base=1, hi=1 << 16)
    t_nops = _undelta(_col(chunks, T_NOPS, n_txns, "op count"),
                      "op count", base=1, lo=1, hi=1 << 18)
    n_parents = sum(t_npar)
    n_ops = sum(t_nops)
    if n_parents > _MAX_PARENTS:
        raise CodecError(f"{n_parents} parents exceed cap {_MAX_PARENTS}")
    if n_ops > _MAX_OPS:
        raise CodecError(f"{n_ops} ops exceed cap {_MAX_OPS}")

    # Op columns first: txn seqs chain over txn LENGTHS, which only the
    # ops know.
    tag_res = _col(chunks, OP_TAG, n_ops, "op tag")
    tags = _undelta(tag_res, "op tag", hi=1)
    n_ins = sum(1 for t in tags if t == 0)
    n_del = n_ops - n_ins

    # Origin columns stay RAW residuals here: their predictors (author
    # index, own-chain seq + emitted, previous-value chains) resolve in
    # the txn assembly loop below, where author/seq are known.
    i_ola_res = _col(chunks, I_OLA, n_ins, "origin-left agent")
    i_ols_res = _col(chunks, I_OLS, n_ins, "origin-left seq")
    i_len = _undelta(_col(chunks, I_LEN, n_ins, "insert length"),
                     "insert length", base=1, lo=1, hi=_MAX_CONTENT)
    n_cp = sum(i_len)
    if n_cp > _MAX_CONTENT:
        raise CodecError(f"{n_cp} codepoints exceed cap {_MAX_CONTENT}")
    ora_res = _col(chunks, I_ORA, n_ins, "origin-right agent")
    ors_res = _col(chunks, I_ORS, n_ins, "origin-right seq")
    cps = _undelta(_col(chunks, CONTENT, n_cp, "content"),
                   "content codepoint", hi=0x10FFFF)
    for cp in cps:
        if 0xD800 <= cp <= 0xDFFF:
            raise CodecError(f"content codepoint {cp:#x} is a surrogate")

    d_agent = _undelta(_col(chunks, D_AGENT, n_del, "delete agent"),
                       "delete agent index",
                       hi=n_names - 1 if n_names else 0)
    d_len = _undelta(_col(chunks, D_LEN, n_del, "delete length"),
                     "delete length", base=1, lo=1)
    # Delete target seq: previous delete's seq + len (a sweep chains).
    d_seq: List[int] = []
    d_chain = 0
    for k, r in enumerate(_col(chunks, D_SEQ, n_del, "delete seq")):
        v = d_chain + r
        if v < 0 or v > _U32_MAX:
            raise CodecError(f"delete seq {v} out of u32 range")
        d_seq.append(v)
        d_chain = v + d_len[k]

    p_agent_res = _col(chunks, P_AGENT, n_parents, "parent agent")
    p_seq_res = _col(chunks, P_SEQ, n_parents, "parent seq")
    t_seq_res = _col(chunks, T_SEQ, n_txns, "txn seq")

    txns: List[RemoteTxn] = []
    oi = ii = di = ci = pi = 0
    chain: Dict[int, int] = {}
    last_seq: Dict[int, int] = {}
    prev_ols = prev_ors = 0
    for ti in range(n_txns):
        author = t_agent[ti]
        seq = chain.get(author, 0) + t_seq_res[ti]
        if seq < 0 or seq > _U32_MAX:
            raise CodecError(f"txn seq {seq} out of u32 range")
        parents: List[RemoteId] = []
        for _ in range(t_npar[ti]):
            pa = author + p_agent_res[pi]
            if pa < 0 or pa >= n_names:
                raise CodecError(
                    f"parent agent index {pa} out of table range {n_names}")
            ps = last_seq.get(pa, seq - 1) + p_seq_res[pi]
            if ps < 0 or ps > _U32_MAX:
                raise CodecError(f"parent seq {ps} out of u32 range")
            parents.append(RemoteId(names[pa], ps))
            pi += 1
        ops: List[Union[RemoteIns, RemoteDel]] = []
        tlen = 0
        emitted = 0
        for _ in range(t_nops[ti]):
            if tags[oi] == 0:
                ola = author + i_ola_res[ii]
                if ola < 0 or ola >= n_names:
                    raise CodecError(
                        f"origin-left agent index {ola} out of "
                        f"table range {n_names}")
                r = i_ols_res[ii]
                if names[ola] == "ROOT":
                    ols = _U32_MAX + r
                elif ola == author:
                    ols = (seq + emitted - 1) + r
                else:
                    ols = prev_ols + r
                    prev_ols = ols
                if ols < 0 or ols > _U32_MAX:
                    raise CodecError(
                        f"origin-left seq {ols} out of u32 range")
                ora = ola + ora_res[ii]
                if ora < 0 or ora >= n_names:
                    raise CodecError(
                        f"origin-right agent index {ora} out of "
                        f"table range {n_names}")
                if names[ora] == "ROOT":
                    ors = _U32_MAX + ors_res[ii]
                else:
                    ors = prev_ors + ors_res[ii]
                    prev_ors = ors
                if ors < 0 or ors > _U32_MAX:
                    raise CodecError(
                        f"origin-right seq {ors} out of u32 range")
                n = i_len[ii]
                text = "".join(map(chr, cps[ci:ci + n]))
                ci += n
                ops.append(RemoteIns(RemoteId(names[ola], ols),
                                     RemoteId(names[ora], ors), text))
                ii += 1
                tlen += n
                emitted += n
            else:
                ln = d_len[di]
                if d_seq[di] + ln > _U32_MAX + 1:
                    raise CodecError(
                        f"delete length {ln} exceeds u32 range")
                ops.append(RemoteDel(RemoteId(names[d_agent[di]],
                                              d_seq[di]), ln))
                di += 1
                tlen += ln
            oi += 1
        txn = RemoteTxn(RemoteId(names[author], seq), parents, ops)
        try:
            validate_remote_txn(txn)
        except ValueError as e:
            # Same span-naming contract as the row decoder: the bytes
            # were sound, so the reject can carry the op's identity.
            raise CodecError(f"invalid txn: {e}", agent=names[author],
                             seq=seq, n=tlen) from None
        txns.append(txn)
        last_seq[author] = seq
        chain[author] = seq + tlen
    return txns


def decode_txns(buf: bytes, cur: int, end: int) -> List[RemoteTxn]:
    """Decode a columnar KIND_TXNS payload body (after the kind byte).

    Raises ``CodecError`` on any structural violation; the caller
    (``codec.decode_frame``) has already CRC-checked the frame.
    """
    buf, cur, end = _unwrap_body(buf, cur, end)
    names, cur = _read_names(buf, cur, end)
    n_txns, cur = _read_varint(buf, cur, end)
    if n_txns > _MAX_TXNS:
        raise CodecError(f"txn count {n_txns} exceeds cap {_MAX_TXNS}")
    if n_txns and not names:
        raise CodecError("txn batch with empty name table")
    chunks, cur = _read_chunks(buf, cur, end, _COLS_TXNS)
    if cur != end:
        raise CodecError(f"{end - cur} trailing bytes after column chunks")
    return _decode_txn_cols(chunks, names, n_txns)


def decode_txns_mux(buf: bytes, cur: int, end: int
                    ) -> List[Tuple[str, List[RemoteTxn]]]:
    """Decode a TXNS_MUX payload body to ``[(doc_id, txns)]`` groups in
    stream order (consecutive same-doc txns grouped; a doc may appear
    in more than one group if the encoder interleaved)."""
    buf, cur, end = _unwrap_body(buf, cur, end)
    doc_ids, cur = _read_names(buf, cur, end)
    if len(doc_ids) > _MAX_DOCS:
        raise CodecError(f"doc table of {len(doc_ids)} exceeds cap "
                         f"{_MAX_DOCS}")
    names, cur = _read_names(buf, cur, end)
    n_txns, cur = _read_varint(buf, cur, end)
    if n_txns > _MAX_TXNS:
        raise CodecError(f"txn count {n_txns} exceeds cap {_MAX_TXNS}")
    if n_txns and not names:
        raise CodecError("txn batch with empty name table")
    if n_txns and not doc_ids:
        raise CodecError("mux batch with empty doc table")
    chunks, cur = _read_chunks(buf, cur, end, _COLS_MUX)
    if cur != end:
        raise CodecError(f"{end - cur} trailing bytes after column chunks")
    doc_col = _undelta(_col(chunks, DOC, n_txns, "doc index"),
                       "doc index", hi=len(doc_ids) - 1 if doc_ids else 0)
    txns = _decode_txn_cols(chunks, names, n_txns)
    return group_consecutive(
        [(doc_ids[di], txn) for di, txn in zip(doc_col, txns)])

"""Core scalar types, sentinels and plain-data op structs.

TPU-native rebuild of the reference's `src/common.rs` and
`src/list/external_txn.rs` data model:

- Agent ids are dense u16 ints, peer-local (`common.rs:5-13`).
- ``CRDTLocation`` = (agent, seq) names one item globally (`common.rs:16-28`).
- Orders are dense u32 op ids, local to this peer (`list/mod.rs:29-30`).
- The ROOT sentinel must be device-representable, so we use u32::MAX /
  u16::MAX sentinels rather than Options (`list/mod.rs:30`, `common.rs:13`).
- ``RemoteTxn`` / ``RemoteOp`` / ``RemoteId`` are the only peer-portable,
  agent-name-carrying structs (`external_txn.rs:5-30`): numeric ids are
  peer-local, so only strings cross the wire (`README.md:33-35`).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Union

# u32::MAX — the virtual "root" item every initial insert attaches to
# (`list/mod.rs:30`).
ROOT_ORDER: int = 0xFFFF_FFFF

# u16::MAX — invalid / ROOT agent id (`common.rs:13`, `doc.rs:68`).
CLIENT_INVALID: int = 0xFFFF

# u32 arithmetic mask for device-parity (orders are u32 on device).
U32_MASK: int = 0xFFFF_FFFF


@dataclass(frozen=True)
class CRDTLocation:
    """(agent, seq) pair naming one inserted item (`common.rs:16-28`)."""

    agent: int = CLIENT_INVALID
    seq: int = 0xFFFF_FFFF


# The root location sentinel (`common.rs:30-33`).
CRDT_DOC_ROOT = CRDTLocation(agent=CLIENT_INVALID, seq=0)


@dataclass
class LocalOp:
    """One local edit: delete ``del_span`` chars at ``pos``, then insert
    ``ins_content`` at ``pos`` (`common.rs:46-50`)."""

    pos: int
    ins_content: str = ""
    del_span: int = 0


@dataclass(frozen=True)
class RemoteId:
    """Peer-portable item id: agent named by string (`external_txn.rs:6-9`)."""

    agent: str
    seq: int


ROOT_REMOTE_ID = RemoteId(agent="ROOT", seq=0xFFFF_FFFF)


@dataclass
class RemoteIns:
    """Remote insert run (`external_txn.rs:13-17`)."""

    origin_left: RemoteId
    origin_right: RemoteId
    ins_content: str


@dataclass
class RemoteDel:
    """Remote delete of ``len`` items starting at ``id`` (`external_txn.rs:19-22`)."""

    id: RemoteId
    len: int


RemoteOp = Union[RemoteIns, RemoteDel]


@dataclass
class RemoteTxn:
    """Peer-portable transaction (`external_txn.rs:25-30`)."""

    id: RemoteId
    parents: List[RemoteId] = field(default_factory=list)
    ops: List[RemoteOp] = field(default_factory=list)


def txn_len(txn: RemoteTxn) -> int:
    """Total item count of a txn = seqs it consumes (`doc.rs:252-257`):
    inserts consume one seq per char, deletes one per deleted item."""
    return sum(
        len(op.ins_content) if isinstance(op, RemoteIns) else op.len
        for op in txn.ops
    )


def validate_remote_txn(txn: RemoteTxn) -> None:
    """Structural validation of a peer-portable txn (`doc.rs:242-269`
    preconditions the apply paths otherwise only assert):

    - at least one op, and total length > 0 (zero-length txns would create
      zero-length RLE log entries and break frontier arithmetic);
    - inserts carry non-empty content; deletes have positive length;
    - no id names the reserved ROOT agent as an *author* (ROOT is only
      valid as an origin/parent sentinel).

    Raises ``ValueError``; the wire codec wraps this into ``CodecError``
    so malformed frames are rejected, never applied.
    """
    if txn.id.agent == "ROOT":
        raise ValueError("txn authored by reserved agent ROOT")
    if not txn.parents:
        # Every legitimate txn has >= 1 parent (ROOT for the first,
        # `doc.rs:54`): a parentless txn would plant a second root in the
        # time DAG and permanently poison the frontier.
        raise ValueError("txn has no parents")
    if not txn.ops:
        raise ValueError("txn has no ops")
    for op in txn.ops:
        if isinstance(op, RemoteIns):
            if not op.ins_content:
                raise ValueError("empty insert run")
        elif isinstance(op, RemoteDel):
            if op.len <= 0:
                raise ValueError(f"non-positive delete length {op.len}")
            if op.id.agent == "ROOT":
                raise ValueError("delete targets the ROOT sentinel")
        else:
            raise ValueError(f"unknown op type {type(op).__name__}")
    if txn_len(txn) <= 0:
        raise ValueError("zero-length txn")


def split_txn_suffix(txn: RemoteTxn, at: int) -> RemoteTxn:
    """The suffix of ``txn`` starting ``at`` ops in (0 < at < txn_len).

    Valid because within one txn, seqs and op offsets advance together
    (`doc.rs:252-269`). Used when merging history that is already partially
    known (`models.sync.merge_into`, `parallel.causal.CausalBuffer`).
    """
    agent = txn.id.agent
    consumed = 0
    suffix_ops: List[RemoteOp] = []
    for op in txn.ops:
        ln = len(op.ins_content) if isinstance(op, RemoteIns) else op.len
        if consumed + ln <= at:
            consumed += ln
            continue
        if consumed >= at:
            suffix_ops.append(op)
            consumed += ln
            continue
        # Split this op.
        off = at - consumed
        if isinstance(op, RemoteIns):
            suffix_ops.append(RemoteIns(
                # Implicit chain: predecessor is (agent, seq+at-1)
                # (`span.rs:24-28`).
                origin_left=RemoteId(agent, txn.id.seq + at - 1),
                origin_right=op.origin_right,
                ins_content=op.ins_content[off:],
            ))
        else:
            suffix_ops.append(RemoteDel(
                id=RemoteId(op.id.agent, op.id.seq + off),
                len=op.len - off,
            ))
        consumed += ln
    return RemoteTxn(
        id=RemoteId(agent, txn.id.seq + at),
        parents=[RemoteId(agent, txn.id.seq + at - 1)],
        ops=suffix_ops,
    )

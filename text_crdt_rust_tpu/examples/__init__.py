"""CLI entry points (the `examples/` drivers of the reference):

- ``python -m text_crdt_rust_tpu.examples.soak`` — 1M seeded random
  edits + stats (`examples/simple.rs:14-49`).
- ``python -m text_crdt_rust_tpu.examples.stats`` — trace replay with
  memory/compaction report (`examples/stats.rs:39-73`).
"""

"""Soak driver: seeded random edits, dual-oracle checked, with stats.

The `examples/simple.rs:14-49` analog: 1M seeded random edits against a
rope oracle, then stats. Here the edits replay on the native C++ engine
(single call), final content is verified against the text-only gap-buffer
replay (`benches/ropey.rs` analog — an independent code path), and the
first ``--oracle`` edits additionally replay step-by-step through the
Python oracle with per-step content equality + ``check()`` invariants
(the `make_random_change`/`doc.check()` loop of `doc.rs:544-587`).

Usage: ``python -m text_crdt_rust_tpu.examples.soak [--edits N] [--seed S]``
"""
from __future__ import annotations

import random
import sys
import time

import numpy as np

from ..common import LocalOp
from ..config import SoakConfig


def make_edits(rng: random.Random, n: int):
    """Seeded random edit stream (the `make_random_change` distribution:
    inserts of 1-4 chars vs deletes of 1-4, position uniform)."""
    pos = np.zeros(n, np.uint32)
    dels = np.zeros(n, np.uint32)
    ilens = np.zeros(n, np.uint32)
    chars = []
    content_len = 0
    alphabet = "abcdefghijklmnop "
    for i in range(n):
        if content_len == 0 or rng.random() < 0.55:
            p = rng.randint(0, content_len)
            ins = "".join(rng.choice(alphabet)
                          for _ in range(rng.randint(1, 4)))
            pos[i] = p
            ilens[i] = len(ins)
            chars.append(ins)
            content_len += len(ins)
        else:
            p = rng.randint(0, content_len - 1)
            span = min(rng.randint(1, 4), content_len - p)
            pos[i] = p
            dels[i] = span
            content_len -= span
    cps = np.frombuffer("".join(chars).encode("utf-32-le"), dtype=np.uint32)
    return pos, dels, ilens, cps


def main(argv=None) -> int:
    cfg = SoakConfig.from_args(argv)
    rng = random.Random(cfg.seed)
    print(f"soak: {cfg.edits} seeded random edits (seed={cfg.seed})")
    pos, dels, ilens, cps = make_edits(rng, cfg.edits)

    # Step-by-step differential oracle on a prefix (`doc.rs:571-587`).
    if cfg.oracle_steps:
        from ..models.oracle import ListCRDT

        doc = ListCRDT(capacity=256)
        agent = doc.get_or_create_agent_id("soak")
        content = ""
        off = 0
        for i in range(min(cfg.oracle_steps, cfg.edits)):
            il = int(ilens[i])
            ins = (cps[off:off + il].tobytes().decode("utf-32-le")
                   if il else "")
            off += il
            p, d = int(pos[i]), int(dels[i])
            doc.apply_local_txn(agent, [LocalOp(p, ins, d)])
            content = content[:p] + ins + content[p + d:]
            assert doc.to_string() == content, f"oracle diverged at {i}"
        doc.check()
        print(f"  oracle prefix OK ({min(cfg.oracle_steps, cfg.edits)} "
              f"steps, per-step checked)")

    # Full run on the native engine.
    from ..models.native import NativeListCRDT, rope_replay

    ndoc = NativeListCRDT()
    agent = ndoc.get_or_create_agent_id("soak")
    t0 = time.perf_counter()
    ndoc.replay_trace(agent, pos, dels, ilens, cps)
    wall = time.perf_counter() - t0
    print(f"  native replay: {cfg.edits / wall:,.0f} edits/s "
          f"({wall * 1e3:.0f}ms)")

    # Independent text-only oracle (different code path entirely).
    n, content = rope_replay(pos, dels, ilens, cps)
    got = ndoc.to_string()
    assert got == content, "native engine diverged from gap-buffer oracle"
    print(f"  content OK: {n} chars, {ndoc.num_spans()} spans "
          f"(compaction {ndoc.raw_len() / max(1, ndoc.num_spans()):.1f} "
          f"items/span)")

    from ..utils.metrics import print_stats

    print_stats(ndoc, detailed=cfg.detailed)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Stats driver: trace replay + memory/compaction report.

The `examples/stats.rs:39-73` analog: replay a shipped editing trace,
assert the final content, and print span/memory/throughput statistics
(the `print_stats` + `TracingAlloc` report, `stats.rs:56-71`).

Usage: ``python -m text_crdt_rust_tpu.examples.stats [--trace NAME]
[--engine native|oracle] [--detailed]``
"""
from __future__ import annotations

import sys
import time

import numpy as np

from ..config import StatsConfig
from ..utils.testdata import flatten_patches, load_testing_data, trace_path


def main(argv=None) -> int:
    cfg = StatsConfig.from_args(argv)
    data = load_testing_data(trace_path(cfg.trace))
    patches = flatten_patches(data)
    n_chars = sum(p.del_len + len(p.ins_content) for p in patches)
    print(f"{cfg.trace}: {len(patches)} patches, {n_chars} CRDT ops, "
          f"final length {len(data.end_content)}")

    if cfg.engine == "native":
        from ..models.native import NativeListCRDT

        doc = NativeListCRDT()
        agent = doc.get_or_create_agent_id("stats")
        pos = [p.pos for p in patches]
        dels = [p.del_len for p in patches]
        ilens = [len(p.ins_content) for p in patches]
        cps = np.frombuffer(
            "".join(p.ins_content for p in patches).encode("utf-32-le"),
            dtype=np.uint32)
        t0 = time.perf_counter()
        doc.replay_trace(agent, pos, dels, ilens, cps)
        wall = time.perf_counter() - t0
    else:
        from ..common import LocalOp
        from ..models.oracle import ListCRDT

        doc = ListCRDT(capacity=1024)
        agent = doc.get_or_create_agent_id("stats")
        t0 = time.perf_counter()
        for p in patches:
            doc.apply_local_txn(
                agent, [LocalOp(p.pos, p.ins_content, p.del_len)])
        wall = time.perf_counter() - t0

    got = doc.to_string()
    ok = got == data.end_content
    print(f"replay ({cfg.engine}): {wall * 1e3:.0f}ms = "
          f"{len(patches) / wall:,.0f} patches/s, final content "
          f"{'OK' if ok else 'MISMATCH'}")
    if not ok:
        return 1

    from ..utils.metrics import print_stats

    print_stats(doc, detailed=cfg.detailed)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Production sync pipeline demo: N documents, each streaming three
peers' remote ops through the causal buffer onto the per-lane engine.

The end-to-end shape a reference user needs for "apply_remote_txn at
scale" (`doc.rs:242-348` × N documents): per doc, three peers edit
concurrently, their RemoteTxns arrive interleaved and OUT OF ORDER
from the network, ``parallel.causal.CausalBuffer`` holds them until
causally ready, ``ops.batch.compile_remote_txns`` turns the released
stream into device steps, and ``ops.rle_lanes_mixed`` applies every
document's own stream — one op per lane per kernel step — with
device-resident state (runs + by-order tables) carried across chunks.
Every chunk is verified against the Python oracle.

Usage::

    python -m text_crdt_rust_tpu.examples.sync_stream \
        [--docs N] [--chunks C] [--ops-per-chunk K] [--seed S] [--cpu]

``--cpu`` runs the kernel in interpret mode on the CPU backend (no TPU
needed) — the default everywhere but a bench box.
"""
from __future__ import annotations

import argparse
import random
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=8)
    ap.add_argument("--chunks", type=int, default=3)
    ap.add_argument("--ops-per-chunk", type=int, default=15,
                    help="patches per peer per chunk")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--cpu", action="store_true", default=True)
    ap.add_argument("--tpu", dest="cpu", action="store_false",
                    help="compile for the attached accelerator")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from ..common import txn_len
    from ..models.oracle import ListCRDT
    from ..models.sync import export_txns_since
    from ..ops import batch as B
    from ..ops import rle_lanes as RL
    from ..ops import rle_lanes_mixed as RLM
    from ..parallel.causal import CausalBuffer
    from ..utils.randedit import random_patches

    rng = random.Random(args.seed)
    n = args.docs
    print(f"sync_stream: {n} docs x {args.chunks} chunks x "
          f"3 peers x {args.ops_per_chunk} patches (seed={args.seed})")

    # Each doc's "network": three peer replicas editing concurrently;
    # their txn streams interleave and arrive shuffled per chunk.
    peers = []
    for d in range(n):
        pair = []
        for name in ("ann", "bob", "cyd"):
            doc = ListCRDT()
            agent = doc.get_or_create_agent_id(name)
            pair.append((doc, agent, [0]))  # [watermark]
        peers.append(pair)

    def peer_chunk(doc, agent, wm):
        patches, _ = random_patches(rng, args.ops_per_chunk)
        # Continue this peer's own replica with fresh random edits.
        for p in patches:
            ln = len(doc)
            pos = min(p.pos, ln)
            if p.del_len and ln:
                doc.local_delete(agent, min(pos, ln - 1),
                                 min(p.del_len, ln - min(pos, ln - 1)))
            if p.ins_content:
                doc.local_insert(agent, min(pos, len(doc)),
                                 p.ins_content)
        txns = export_txns_since(doc, wm[0])
        wm[0] = doc.get_next_order()
        return txns

    import numpy as np

    buffers = [CausalBuffer() for _ in range(n)]
    tables = [B.AgentTable() for _ in range(n)]
    assigners = [None] * n
    oracles = [ListCRDT() for _ in range(n)]
    state = None
    rkl_acc = None  # host-accumulated author ranks: the YATA tiebreak
    #                 reads EXISTING items' ranks from the read-only rkl
    #                 input, so earlier chunks' entries must stay visible
    applied_txns = 0
    applied_ops = 0
    total_steps = 0
    t0 = time.perf_counter()
    for c in range(args.chunks):
        opses = []
        for d in range(n):
            arrivals = []
            for doc, agent, wm in peers[d]:
                arrivals.extend(peer_chunk(doc, agent, wm))
            rng.shuffle(arrivals)  # the network reorders
            released = buffers[d].add_all(arrivals)
            for t in released:
                tables[d].add(t.id.agent)
                oracles[d].apply_remote_txn(t)
            ops, assigners[d] = B.compile_remote_txns(
                released, tables[d], assigner=assigners[d], lmax=8,
                dmax=None)
            opses.append(ops)
            applied_txns += len(released)
            applied_ops += sum(txn_len(t) for t in released)
        stacked = B.stack_ops(opses)
        # Rows accumulate across chunks (<= 2 per compiled step), so
        # the capacity bound is CUMULATIVE steps, not this chunk's.
        total_steps += stacked.num_steps
        capacity = ((1 + 2 * total_steps + 63) // 64) * 64
        adv = int(np.asarray(stacked.order_advance,
                             np.int64).sum(axis=0).max())
        base = rkl_acc.shape[0] if rkl_acc is not None else 0
        ocap = ((base + adv + 8 + 7) // 8) * 8
        _, _, rkl_c = RLM.lane_tables(stacked, ocap)
        if rkl_acc is not None:
            grown = np.zeros((ocap, n), np.int32)
            grown[: rkl_acc.shape[0]] = rkl_acc
            rkl_acc = np.where(rkl_c != 0, rkl_c, grown)
        else:
            rkl_acc = rkl_c
        run = RLM.make_replayer_lanes_mixed(
            stacked, capacity=capacity, order_capacity=ocap,
            chunk=16, init=state, rkl=rkl_acc, interpret=args.cpu)
        res = run()
        res.check()
        state = res.state()

        for d in range(n):
            want = [(-1 if oracles[d].deleted[i] else 1)
                    * (int(oracles[d].order[i]) + 1)
                    for i in range(oracles[d].n)]
            got = RL.expand_lane(res, d).tolist()
            assert got == want, f"doc {d} diverged from oracle"
        print(f"  chunk {c + 1}/{args.chunks}: {applied_txns} txns / "
              f"{applied_ops} char-ops applied, capacity {capacity}, "
              f"all {n} docs == oracle")
    for d in range(n):
        assert buffers[d].pending == 0, (
            f"doc {d}: {buffers[d].pending} txns never became ready "
            f"({buffers[d].missing()})")
    wall = time.perf_counter() - t0
    print(f"  done: {applied_txns} remote txns ({applied_ops} char-ops) "
          f"across {n} docs in {wall:.1f}s; every chunk oracle-checked")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Round-3 step-1 measurement: batch sweep x (merged vs unmerged) op stream
on the round-2 HBM engine, real TPU. Writes perf/sweep_r3.json."""
import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json, sys, time
import numpy as np
import jax
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import blocked_hbm as BH
from text_crdt_rust_tpu.utils.testdata import load_testing_data, trace_path, flatten_patches

data = load_testing_data(trace_path("automerge-paper"))
patches = flatten_patches(data)
n_ops = len(patches)
rows = []
for label, plist, lmax in (("unmerged", patches, 16),
                           ("merged", B.merge_patches(patches), 128)):
    ops, _ = B.compile_local_patches(plist, lmax=lmax, dmax=None)
    print(f"{label}: {ops.num_steps} steps", file=sys.stderr, flush=True)
    for batch in (128, 256, 512, 1024):
        try:
            run = BH.make_replayer_hbm(ops, capacity=524288, batch=batch,
                                       block_k=512, chunk=1024)
            t0 = time.perf_counter(); res = run(); res.check()
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(3):
                res = run()
            res.check()
            wall = (time.perf_counter() - t0) / 3
            v = n_ops * batch / wall
            rows.append(dict(stream=label, batch=batch, steps=ops.num_steps,
                             wall_s=round(wall, 4),
                             step_us=round(wall / ops.num_steps * 1e6, 3),
                             ops_per_sec=round(v, 1),
                             vs_base=round(v / 2.09e6, 2)))
            print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
        except Exception as e:
            rows.append(dict(stream=label, batch=batch, error=str(e)[:200]))
            print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
with open("perf/sweep_r3.json", "w") as f:
    json.dump(rows, f, indent=1)

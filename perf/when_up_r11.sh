#!/bin/bash
# Round-11 recovery watcher (ISSUE 11 / ROADMAP #1): supersedes
# when_up_r10.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> fused serve-lanes loadgen
# smoke -> kevin full 5M -> the remaining rows via --merge-rows — then
# the COST LEDGER device re-record.  New in r11: the ledger --device
# pass now ALSO appends the `flow-device` cell (per-op provenance on
# the chip: op-age-at-apply is logical-tick exact, so silicon must
# reproduce the committed cpu `flow` cell's ages bit for bit — the
# cross-backend proof that per-op latency accounting is device-
# independent — plus the run's wall as an informational band).
# bench.py --check-ledger re-runs once at the end so a drifted cpu
# cell is caught in the same session that recorded silicon.  Safe to
# re-run; appends to perf/when_up_r11.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r11 watcher)" >> perf/when_up_r11.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r11)" >> perf/when_up_r11.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r11.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r11.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r11.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r11.log; exit 1; }
# Second gate: a fused serve-lanes loadgen smoke — the blocked mixed
# kernel's fused splice + the serve stack's fused ticks on device.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r11.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r11.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r11.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r11.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter (serve/serve-lanes rows now
# carry the additive flow_* provenance fields).
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r11.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r11.log
done
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r11.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r11.log
# And prove the cpu contract still holds from this very checkout.
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r11.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r11.log
echo "$(date -u +%H:%M:%S) r11 re-record done" >> perf/when_up_r11.log

"""Replication-bytes + checkpoint-bytes probe (ISSUE 7 acceptance):
the SAME seeded 200-doc serve loadgen run on both replication protocol
generations —

- **v1 (row/full)**: per-event row frames of <= 4 txns, each agent
  re-shipping its merged export, one O(doc) full snapshot per evict
  (the PR-1/PR-3 system exactly as it stood);
- **v2 (columnar/delta)**: deduplicated per-world outboxes flushed
  each resync window as doc-multiplexed columnar frames on one
  connection (``net/columnar`` TXNS_MUX: per-column delta + RLE +
  LEB128, whole-body DEFLATE), pull re-delivery as columnar streams,
  and CRC-chained delta checkpoints writing O(ops since last save)
  per evict —

on both loadgen workloads (``scatter`` random edits, ``typing`` cursor
runs — the real-editing-trace shape).  Every run must end with every
doc bit-identical to its always-resident twin and every device lane
bit-identical to its host oracle (the loadgen's built-in verifier —
the PR-3/PR-4 safety net that makes the aggressive encoding change
safe), the replicated op count must be IDENTICAL across protocol
generations (traffic generation is protocol- and server-state-
independent), and the acceptance bars are:

- wire: v2 bytes-per-replicated-op >= 5x smaller than v1 on at least
  one workload (recorded per workload);
- checkpoints: the mean delta-link evict in the v2 run >= 5x smaller
  than the mean full-snapshot evict in the v1 run, with the delta
  scaling with ops-since-last-save, not doc size.

Writes ``perf/columnar_wire_r10.json``.

Run: python perf/columnar_wire_probe.py [--smoke] [--out PATH]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

GENERATIONS = (("row", "full"), ("columnar", "delta"))
WORKLOADS = ("scatter", "typing")
FAULT_RATES = (0.10, 0.0)   # the acceptance shape AND the clean
#                             steady-state replication cost
FLOOR_X = 5.0


def run_one(workload: str, wire: str, ckpt: str, smoke: bool,
            fault_rate: float = 0.10, seed: int = 7) -> dict:
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=16,
                      wire_format=wire, ckpt_format=ckpt)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                       events_per_tick=events, zipf_alpha=1.1,
                       fault_rate=fault_rate, local_prob=0.25, seed=seed,
                       cfg=cfg, workload=workload)
    t0 = time.perf_counter()
    rep = gen.run()
    assert rep["converged"], (workload, wire, rep["mismatches"][:4])
    srv = rep["server"]
    return {
        "converged": rep["converged"],
        "wall_s": round(time.perf_counter() - t0, 1),
        "docs": docs, "ticks": ticks, "events_per_tick": events,
        "wire": rep["wire"],
        "ckpt": rep["ckpt"],
        "evictions": srv.get("evictions", 0),
        "restores": srv.get("restores", 0),
        "ckpt_full_bytes_per_evict": round(srv.get(
            "ckpt_full_bytes_per_evict_mean", 0.0), 1),
        "ckpt_delta_bytes_per_evict": round(srv.get(
            "ckpt_delta_bytes_per_evict_mean", 0.0), 1),
        "ckpt_saves_full": srv.get("ckpt_saves_full", 0),
        "ckpt_saves_delta": srv.get("ckpt_saves_delta", 0),
        "item_ops_applied": rep["item_ops_applied"],
    }


def run_matrix(smoke: bool = False, seed: int = 7) -> dict:
    out = {"seed": seed, "smoke": smoke, "cells": {}}
    wire_cuts = {}
    ckpt_cuts = {}
    for workload in WORKLOADS:
        for fault_rate in FAULT_RATES:
            runs = {}
            for wire, ckpt in GENERATIONS:
                runs[wire] = run_one(workload, wire, ckpt, smoke,
                                     fault_rate, seed)
            v1, v2 = runs["row"], runs["columnar"]
            assert (v1["wire"]["ops_replicated"]
                    == v2["wire"]["ops_replicated"]), (
                "traffic generation leaked protocol state")
            wire_cut = (v1["wire"]["bytes_per_op"]
                        / max(v2["wire"]["bytes_per_op"], 1e-9))
            full_evict = v1["ckpt_full_bytes_per_evict"]
            delta_evict = v2["ckpt_delta_bytes_per_evict"]
            ckpt_cut = full_evict / max(delta_evict, 1e-9) \
                if delta_evict else 0.0
            cell = f"{workload}/faults={fault_rate}"
            wire_cuts[cell] = round(wire_cut, 2)
            ckpt_cuts[cell] = round(ckpt_cut, 2)
            out["cells"][cell] = {
                "runs": runs,
                "bytes_per_op_row": v1["wire"]["bytes_per_op"],
                "bytes_per_op_columnar": v2["wire"]["bytes_per_op"],
                "wire_bytes_cut_x": round(wire_cut, 2),
                "ckpt_full_bytes_per_evict": full_evict,
                "ckpt_delta_bytes_per_evict": delta_evict,
                "ckpt_evict_bytes_cut_x": round(ckpt_cut, 2),
            }
    out["claims"] = {
        "floor_x": FLOOR_X,
        "wire_bytes_cut_x": wire_cuts,
        "wire_cut_headline_x": max(wire_cuts.values()),
        "wire_cut_meets_floor": max(wire_cuts.values()) >= FLOOR_X,
        "ckpt_evict_bytes_cut_x": ckpt_cuts,
        "ckpt_cut_headline_x": max(ckpt_cuts.values()),
        "ckpt_cut_meets_floor": min(
            v for c, v in ckpt_cuts.items() if "0.1" in c) >= FLOOR_X,
        "all_converged": True,  # run_one asserts per run
    }
    out["note"] = (
        "CPU flat-backend runs (the serving loop is host+interpret "
        "here; wire/ckpt bytes are backend-independent). bytes_per_op "
        "= txn-lane bytes handed to the transport / deduplicated "
        "replicated item-ops; control lane (DIGEST/REQUEST) counted "
        "separately in each run's wire block. The v1 baseline is the "
        "PR-1 protocol exactly as previously shipped. faults=0.0 is "
        "the steady-state replication cost; faults=0.1 (drop + dup + "
        "reorder + truncate + bit-flip EACH at 10% -> ~27% of frames "
        "damaged) adds each protocol's recovery traffic on top — the "
        "wire headline comes from the typing workload (the real-"
        "editing-trace shape), the checkpoint floor must hold on the "
        "faulted acceptance shape itself.")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape (tier-1 smoke); not committed")
    ap.add_argument("--out", default="perf/columnar_wire_r10.json")
    a = ap.parse_args(argv)
    out = run_matrix(smoke=a.smoke)
    if not a.smoke:
        with open(a.out, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
    print(json.dumps(out["claims"], indent=1))
    ok = (out["claims"]["wire_cut_meets_floor"]
          and out["claims"]["ckpt_cut_meets_floor"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

#!/bin/bash
# Round-10 recovery watcher (ISSUE 10 / ROADMAP #1): supersedes
# when_up_r9.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> fused serve-lanes loadgen
# smoke -> kevin full 5M -> the remaining rows via --merge-rows — then
# adds the COST LEDGER device re-record: after the bench rows land,
# perf/cost_ledger_probe.py --device appends the silicon cells (per-
# bucket device-step wall histograms + real-HLO flat-kernel costs on
# the chip) to perf/COST_LEDGER.json WITHOUT touching the committed
# cpu cells, and bench.py --check-ledger re-runs once at the end so a
# drifted cpu cell is caught in the same session that recorded silicon
# (every row merged here is stamped ledger_version — a drifted ledger
# schema refuses the merge).  Safe to re-run; appends to
# perf/when_up_r10.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r10 watcher)" >> perf/when_up_r10.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r10)" >> perf/when_up_r10.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r10.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r10.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r10.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r10.log; exit 1; }
# Second gate: a fused serve-lanes loadgen smoke — the blocked mixed
# kernel's fused splice + the serve stack's fused ticks on device.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r10.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r10.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r10.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r10.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter.
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r10.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r10.log
done
# NEW in r10: the cost-ledger silicon cells — device-step wall
# histograms + real-HLO costs on the chip, appended to the committed
# ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r10.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r10.log
# And prove the cpu contract still holds from this very checkout.
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r10.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r10.log
echo "$(date -u +%H:%M:%S) r10 re-record done" >> perf/when_up_r10.log

"""Bench the sharded SpDoc engine (bench.py --config sp backend).

One committed row for the sequence-parallel engine (VERDICT r5 missing
#5 / next #6): the automerge-paper replay on ``SpDoc`` at virtual sp=8
(CPU mesh — the same mesh shape ``dryrun_multichip`` validates), plus
sp=1 parity against ``ops/rle``'s final state, with an EXPLICIT
collectives-per-op count read off the compiled HLO (the ICI cost model,
stated before real multi-chip exists).

Runs in its own process because the sp mesh needs
``xla_force_host_platform_device_count`` set before the CPU client
exists; bench.py shells out here (the ``probe_device`` subprocess
pattern). Prints one JSON object per row on stdout.

    python perf/sp_bench.py [--patches 2000] [--smoke] [--skip-parity]
"""
import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import rle as R  # noqa: E402
from text_crdt_rust_tpu.ops import span_arrays as SA  # noqa: E402
from text_crdt_rust_tpu.parallel import make_mesh  # noqa: E402
from text_crdt_rust_tpu.parallel.sp_apply import SpDoc  # noqa: E402
from text_crdt_rust_tpu.utils.testdata import (  # noqa: E402
    flatten_patches,
    load_testing_data,
    trace_path,
)

# Collective op spellings across HLO/StableHLO renderings.
_COLLECTIVE_RE = re.compile(
    r"all-gather|all_gather|all-reduce|all_reduce|collective-permute|"
    r"collective_permute|all-to-all|all_to_all", re.IGNORECASE)


def expected_content(patches) -> str:
    s = ""
    for p in patches:
        s = s[:p.pos] + p.ins_content + s[p.pos + p.del_len:]
    return s


def sp_cols(ops):
    """The exact column tuple ``SpDoc.apply_stream`` feeds the jitted
    replay (duplicated here to lower the SAME computation for the
    collective count)."""
    return tuple(
        jnp.asarray(np.asarray(c, dtype=np.uint32).view(np.int32))
        for c in (ops.kind, ops.pos, ops.del_len, ops.del_target,
                  ops.origin_left, ops.origin_right, ops.rank,
                  ops.ins_len, ops.ins_order_start))


def count_collectives(sdoc: SpDoc, ops) -> dict:
    """Static per-step collective count off the compiled HLO: the scan
    body is emitted once, so textual occurrences = collectives per
    device step (every step pays them; XLA does not specialize by op
    kind inside the scan)."""
    lowered = sdoc._replay.lower(
        sdoc.ordp, sdoc.lenp, sdoc.rows, sdoc.oll, sdoc.orl, sdoc.rkl,
        *sp_cols(ops))
    try:
        text = lowered.compile().as_text()
    except Exception:
        text = lowered.as_text()
    hits = _COLLECTIVE_RE.findall(text)
    kinds = {}
    for h in hits:
        k = h.lower().replace("_", "-")
        kinds[k] = kinds.get(k, 0) + 1
    return {"collectives_per_step": len(hits),
            "collectives_by_kind": kinds}


def run_sp(patches, want, nsp, label, count_comms, chunks=4):
    """Chunked streaming apply with ``auto_reshard``: a fresh SpDoc
    holds every live rank in shard 0, so long streams MUST rebalance
    between chunks (the host-side B-tree-rebuild analog) — sizing each
    shard for post-balance occupancy + one chunk's worst-case growth
    (<= 2 rows per compiled step, ``batch.row_growth_bound``)."""
    merged = B.merge_patches(patches)
    lmax = max([len(p.ins_content) for p in merged] + [1])
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    peak, _ = R.simulate_run_rows(merged)
    s_chunk = -(-ops.num_steps // chunks)
    ops_chunks = [
        B.pad_ops(jax.tree.map(lambda a: np.asarray(a)[i:i + s_chunk], ops),
                  s_chunk)
        for i in range(0, ops.num_steps, s_chunk)
    ]
    mesh = make_mesh(n_devices=nsp, dp=1, sp=nsp)
    shard_rows = ((int(peak * 2.5) // nsp + 2 * s_chunk) // 8 + 2) * 8
    # Local-only streams never read the order tables; keep them small.
    sdoc = SpDoc(mesh, shard_rows=shard_rows, order_rows=64,
                 auto_reshard=True)

    def replay():
        sdoc.load(np.zeros(0, np.int32), np.zeros(0, np.int32))
        for ch in ops_chunks:
            sdoc.apply_stream(ch)

    t0 = time.perf_counter()
    replay()   # includes the one-time compile
    first = time.perf_counter() - t0
    got = sdoc.to_string([ops])
    assert got == want, f"{label}: sp replay diverged from string oracle"
    occupied = [int(r) for r in np.asarray(sdoc.rows)]
    # Timed pass on the warm kernel, from empty state.
    t0 = time.perf_counter()
    replay()
    wall = time.perf_counter() - t0
    assert sdoc.to_string([ops]) == want
    row = {
        "label": label,
        "sp": nsp,
        "ops": len(patches),
        "device_steps": int(ops.num_steps),
        "chunks": len(ops_chunks),
        "wall_s": round(wall, 4),
        "first_run_s_incl_compile": round(first, 4),
        "ops_per_sec": round(len(patches) / wall, 1),
        "shard_rows": shard_rows,
        "peak_run_rows": int(peak),
        "rows_per_shard_final": occupied,
        "hbm_bytes_accounted": int(nsp * (2 * shard_rows + 3 * 64) * 4),
        "oracle_equal": True,
    }
    if count_comms:
        row.update(count_collectives(sdoc, ops_chunks[0]))
        row["collectives_per_op"] = round(
            row["collectives_per_step"] * ops.num_steps / len(patches), 3)
    return row, sdoc, ops


def rle_parity(patches, want, interpret=True):
    """sp=1 vs ops/rle: identical final content from the same merged
    stream (the parity bar; rle runs interpret on CPU, so only content
    is compared — relative throughput needs silicon)."""
    merged = B.merge_patches(patches)
    lmax = max([len(p.ins_content) for p in merged] + [1])
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    peak, _ = R.simulate_run_rows(merged)
    capacity = ((int(peak * 2.5) + 255) // 256) * 256
    run = R.make_replayer_rle(ops, capacity=max(capacity, 512), batch=8,
                              block_k=64, chunk=128, interpret=interpret)
    res = run()
    res.check()
    got = SA.to_string(R.rle_to_flat(ops, res))
    assert got == want, "ops/rle replay diverged"
    return got


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--patches", type=int, default=2000,
                    help="automerge-paper prefix length")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the interpret-mode ops/rle parity pass")
    a = ap.parse_args()

    n = 400 if a.smoke else a.patches
    data = load_testing_data(trace_path("automerge-paper"))
    patches = flatten_patches(data)[:n]
    want = expected_content(patches)

    row8, _, _ = run_sp(patches, want, nsp=8,
                        label="config_sp_automerge_sp8_virtual",
                        count_comms=True)
    row8["note"] = ("virtual 8-device CPU mesh (no ICI): ops/s is a "
                    "host-mesh logic number; collectives_per_step is the "
                    "static ICI cost model")
    print(json.dumps(row8), flush=True)

    row1, _, _ = run_sp(patches, want, nsp=1,
                        label="config_sp_parity_sp1", count_comms=False)
    if not a.skip_parity:
        parity_n = min(n, 400)
        parity_patches = patches[:parity_n]
        rle_parity(parity_patches, expected_content(parity_patches))
        row1["rle_parity"] = f"content-equal vs ops/rle on {parity_n} patches"
    print(json.dumps(row1), flush=True)


if __name__ == "__main__":
    main()

"""PR-16 acceptance run: the full crash matrix at the serve-200 shape.

Every kill phase x fault rate {0, 10%} at docs=200 / 2 shards x 16
lanes, crash at tick 30 of 60.  A cell is green when the recovered
server's logical streams are sha256-identical to the uncrashed
same-seed twin, the resumed workload converges, and the crash-boundary
flow audit passes at recovery AND at the end of the run.  Writes
``perf/crash_matrix_r15.json`` (the PERF.md §21 table source).

Run:  JAX_PLATFORMS=cpu python perf/crash_matrix_r15.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from text_crdt_rust_tpu.serve.chaos import run_crash_matrix  # noqa: E402

SHAPE = dict(crash_tick=30, ticks=60, docs=200, agents_per_doc=3,
             events_per_tick=48, seed=7, num_shards=2,
             lanes_per_shard=16, ckpt_format="delta")


def main() -> int:
    t0 = time.time()
    out = run_crash_matrix(**SHAPE)
    wall = time.time() - t0
    rows = {}
    for key, cell in out["cells"].items():
        row = dict(cell)
        row["journal_bytes_per_op"] = round(row["journal_bytes_per_op"], 3)
        row["recover_wall_s"] = round(row["recover_wall_s"], 3)
        rows[key] = row
    doc = {"shape": SHAPE, "ok": out["ok"], "wall_s": round(wall, 1),
           "cells": rows}
    path = os.path.join(os.path.dirname(__file__), "crash_matrix_r15.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"ok": out["ok"], "wall_s": doc["wall_s"],
                      "cells": {k: v["green"] for k, v in rows.items()}},
                     indent=1))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

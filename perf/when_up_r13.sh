#!/bin/bash
# Round-13 recovery watcher (ISSUE 13 / ROADMAP #1): supersedes
# when_up_r12.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> pipelined serve device
# smoke -> fused serve-lanes smoke -> kevin full 5M -> the remaining
# rows via --merge-rows -> the COST LEDGER device re-record.  New in
# r13: a SANITIZED pipelined serve device smoke right after the plain
# pipelined one — the aliasing sanitizer's first silicon run.  On a
# real chip async dispatch is genuinely asynchronous (device steps
# take ~ms, not the CPU formality), so this is where a host write
# racing an in-flight step would actually corrupt: the sanitizer must
# come up clean there AND stay byte-identical, or the pipelined tick
# is not safe at silicon latencies.  Safe to re-run; appends to
# perf/when_up_r13.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r13 watcher)" >> perf/when_up_r13.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r13)" >> perf/when_up_r13.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r13.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r13.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r13.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r13.log; exit 1; }
# Pipelined serve device smoke: the double-buffered tick on the flat
# backend, on-device — the staged sync overlapping real device steps,
# convergence + lane bit-identity still green.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  >> perf/when_up_r13.log 2>&1 \
  || { echo "pipelined serve device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r13.log; exit 1; }
# SANITIZED pipelined serve device smoke (new in r13): the aliasing
# sanitizer under real async dispatch.  A failure here is a REAL
# host-write-races-device-step bug the CPU arms could never exhibit —
# stop the chain and read the named tick/shard/array.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --sanitize-pipeline \
  >> perf/when_up_r13.log 2>&1 \
  || { echo "SANITIZED pipelined device smoke FAILED rc=$? - aliasing " \
            "race on silicon? NOT re-recording" \
         >> perf/when_up_r13.log; exit 1; }
# Fused serve-lanes loadgen smoke — the blocked mixed kernel's fused
# splice + the serve stack's fused ticks on device (the lanes backend
# clamps the pipeline to serial; that clamp is part of the smoke).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r13.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r13.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r13.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r13.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter.
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r13.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r13.log
done
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r13.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r13.log
# And prove the cpu contracts still hold from this very checkout:
# cost ledger + the tcrlint gate (a drifted tree must not re-record).
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r13.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r13.log
timeout 600 env JAX_PLATFORMS=cpu python -m text_crdt_rust_tpu.analysis.lint \
  >> perf/when_up_r13.log 2>&1 \
  || echo "TCRLINT FAILED rc=$? - determinism/schema finding on this checkout" \
       >> perf/when_up_r13.log
echo "$(date -u +%H:%M:%S) r13 re-record done" >> perf/when_up_r13.log

import sys, os; sys.path.insert(0, "/root/repo")
import time
import numpy as np
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.utils.testdata import load_testing_data, trace_path, flatten_patches

data = load_testing_data(trace_path("automerge-paper"))
patches = flatten_patches(data)
merged = B.merge_patches(patches)
lmax = max(len(p.ins_content) for p in merged if p.ins_content)
ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)

for batch, cap, bk in ((512, 20480, 128), (384, 24576, 128)):
    try:
        run = R.make_replayer_rle(ops, capacity=cap, batch=batch,
                                  block_k=bk, chunk=1024)
        t0 = time.perf_counter()
        res = run(); np.asarray(res.err); res.check()
        print(f"B={batch} compile+first {time.perf_counter()-t0:.1f}s", flush=True)
        t0 = time.perf_counter()
        for _ in range(6): res = run()
        np.asarray(res.err)
        t8 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(2): res = run()
        np.asarray(res.err)
        wall = (t8 - (time.perf_counter() - t0)) / 4
        v = 259778 * batch / wall
        print(f"B={batch} cap={cap} K={bk}: {wall*1e3:.1f}ms {v/2.09e6:.0f}x", flush=True)
    except Exception as e:
        print(f"B={batch} cap={cap} K={bk}: FAIL {str(e)[:90]}", flush=True)

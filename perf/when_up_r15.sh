#!/bin/bash
# Round-15 recovery watcher (ISSUE 16 / durability): supersedes
# when_up_r14.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> device-prefill pipelined
# serve smoke -> host-prefill arm -> sanitized pipelined smoke ->
# fused serve-lanes smoke -> kevin full 5M -> remaining rows ->
# cost-ledger device re-record.  New in r15: TWO recovery-on-device
# smokes run before any re-record is trusted — (1) a JOURNALED
# pipelined device run (the write-ahead journal on the hot path under
# real async dispatch: the admission-edge append must not perturb the
# logical stream, and convergence must hold with fsync-per-tick on),
# and (2) a full crash/recover/resume cycle ON DEVICE via
# --crash-at post-dispatch (kill with a depth-2 pipeline in flight,
# replay the journal through the normal admission path, re-derive the
# crashed tick, byte-compare against the uncrashed same-seed twin) —
# on CPU this matrix is tier-1-proven (PERF.md §21); on silicon it is
# the first time recovery replays REAL dispatched work.  Safe to
# re-run; appends to perf/when_up_r15.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r15 watcher)" >> perf/when_up_r15.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r15)" >> perf/when_up_r15.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r15.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r15.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r15.log; exit 1; }
# DEVICE-PREFILL pipelined serve smoke: the delta scatter +
# double-buffered tick on real async dispatch.  Convergence + lane
# bit-identity must hold before anything else is trusted.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "device-prefill pipelined serve smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r15.log; exit 1; }
# The HOST-PREFILL arm of the same seed: the two prefill paths must
# stay byte-identical on silicon too (the ISSUE-14 contract the CPU
# suite pins; a divergence here is a chip-side scatter bug).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --host-prefill \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "host-prefill serve smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r15.log; exit 1; }
# SANITIZED pipelined serve device smoke: the aliasing sanitizer under
# real async dispatch.  A failure here is a REAL
# host-write-races-device-step bug the CPU arms could never exhibit.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --sanitize-pipeline \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "SANITIZED pipelined device smoke FAILED rc=$? - aliasing " \
            "race on silicon? NOT re-recording" \
         >> perf/when_up_r15.log; exit 1; }
# JOURNALED pipelined device smoke (new in r15): the write-ahead
# journal appending at the admission edge while real async device
# steps are in flight.  The journal is host-side and logically
# invisible by construction — this proves it stays that way when
# dispatch is genuinely asynchronous (convergence gate; the journal
# fsyncs every tick).
rm -rf /tmp/tcr_r15_journal && mkdir -p /tmp/tcr_r15_journal
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  --journal-dir /tmp/tcr_r15_journal --journal-fsync-ticks 1 \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "JOURNALED pipelined device smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r15.log; exit 1; }
# CRASH/RECOVER device smoke (new in r15): kill post-dispatch with a
# depth-2 pipeline in flight, recover a FRESH server from the journal
# (replay through the normal admission path, re-derive the crashed
# tick), resume the workload, and byte-compare logical streams
# against the uncrashed same-seed twin — the PERF.md §21 contract,
# first time on real hardware.  Exit 1 = digests differ or a
# crash-boundary flow audit finding; NOT re-recording on that.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 16 --ticks 10 --crash-at post-dispatch:5 \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "device CRASH/RECOVER smoke FAILED rc=$? - recovery " \
            "divergence on silicon? NOT re-recording" \
         >> perf/when_up_r15.log; exit 1; }
# Fused serve-lanes loadgen smoke — the blocked mixed kernel's fused
# splice + the serve stack's fused ticks on device; the lanes backend
# PIPELINES at depth 2 (host-mirrored row true-up), so this smoke
# also exercises its staged sync on real hardware.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r15.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r15.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r15.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r15.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter.
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r15.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r15.log
done
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r15.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r15.log
# And prove the cpu contracts still hold from this very checkout:
# cost ledger (now including the recovery + flash-crowd cells) + the
# tcrlint gate (a drifted tree must not re-record).
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r15.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r15.log
timeout 600 env JAX_PLATFORMS=cpu python -m text_crdt_rust_tpu.analysis.lint \
  >> perf/when_up_r15.log 2>&1 \
  || echo "TCRLINT FAILED rc=$? - determinism/schema finding on this checkout" \
       >> perf/when_up_r15.log
echo "$(date -u +%H:%M:%S) r15 re-record done" >> perf/when_up_r15.log

"""Per-op provenance probe (ISSUE 11 acceptance): the conservation
audit, flow byte-determinism, and the flow tracer's overhead matrix at
the 200-doc faulted acceptance shape.

Three arms of the SAME seeded loadgen (the §14 probe pattern —
``perf/obs_overhead_probe.py`` — with flow-specific arms):

- ``off``     — ``flow_sample_mod=0``: tracing on (the shipped PR-8
  default), zero flow events — the overhead baseline;
- ``default`` — ``flow_sample_mod=16`` (the shipped default): ~1/16 of
  agents span-tracked end to end;
- ``full``    — ``flow_sample_mod=1``: EVERY emitted op tracked.  This
  arm is the acceptance run: drops/dups/reorders at 10% per fault
  class make leaks likely, and the conservation audit must still
  terminally account every span (zero leaked, zero double-applied)
  after the anti-entropy drain.

Timing arms take the min of ``reps`` runs (default 3 — the committed
artifact's protocol; min-of-N against shared-box noise; loop wall
``device_ticks_wall_s`` is the basis).  Two untimed
``full`` runs additionally pin same-seed byte-identity of the logical
stream INCLUDING flow events, at the full 200-doc shape.

Acceptance: default-sampling overhead < 5% (the PERF.md §14 bar),
audit green at full sampling, streams byte-identical.  Writes
``perf/flow_r13.json``.

Run: python perf/flow_probe.py [--smoke] [--reps N] [--out PATH]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

FLOOR_PCT = 5.0
ARMS = {"off": 0, "default": 16, "full": 1}


def run_one(sample_mod: int, smoke: bool, seed: int = 7,
            keep_trace: bool = False):
    """One seeded loadgen run at the given flow sampling; returns
    (report, logical_trace_bytes)."""
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=16,
                      flow_sample_mod=sample_mod, trace_keep=keep_trace)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                      events_per_tick=events, zipf_alpha=1.1,
                      fault_rate=0.10, local_prob=0.25, seed=seed,
                      cfg=cfg)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    trace_bytes = (gen.server.tracer.logical_bytes()
                   if keep_trace else None)
    return rep, trace_bytes


def run_matrix(smoke: bool = False, reps: int = 3) -> dict:
    arms = {}
    timings = {a: [] for a in ARMS}
    for arm, mod in ARMS.items():
        for _r in range(reps):
            # Timed arms never set trace_keep (the §14 discipline: the
            # shipped default pays ring-only retention).
            t0 = time.perf_counter()
            rep, _ = run_one(mod, smoke)
            wall = time.perf_counter() - t0
            timings[arm].append({
                "total_wall_s": round(wall, 3),
                "loop_wall_s": rep["device_ticks_wall_s"],
            })
            arms[arm] = rep

    # Byte-determinism of the FULL flow stream at this shape, on two
    # untimed runs (flow events are logical-only, so the whole stream
    # must stay byte-identical).
    _repa, trace_a = run_one(1, smoke, keep_trace=True)
    _repb, trace_b = run_one(1, smoke, keep_trace=True)
    trace_identical = trace_a == trace_b

    flow_full = arms["full"]["flow"]
    flow_default = arms["default"]["flow"]
    loops = {a: min(t["loop_wall_s"] for t in timings[a]) for a in ARMS}
    overhead = {
        a: round((loops[a] - loops["off"]) / loops["off"] * 100.0, 2)
        for a in ("default", "full")
    }
    out = {
        "probe": "flow_provenance",
        "smoke": smoke,
        "workload": {
            "docs": arms["full"]["docs"], "seed": 7, "engine": "flat",
            "fault_rate": 0.10, "reps_per_arm": reps,
            "basis": "min loop wall (device_ticks_wall_s) per arm",
            "arms": dict(ARMS),
        },
        "loop_wall_s": {a: round(loops[a], 3) for a in ARMS},
        "overhead_pct": overhead,
        "audit": {
            "full": {
                "ok": flow_full["audit_ok"],
                "spans": flow_full["spans"],
                "duplicates": flow_full["duplicates"],
                "leaks": flow_full["leaks"],
                "findings": flow_full["findings"][:4],
            },
            "default": {
                "ok": flow_default["audit_ok"],
                "spans": flow_default["spans"],
            },
        },
        "ages_ticks": flow_full["ages_ticks"],
        "age_by_band": flow_full["by_band"],
        "age_by_class": flow_full["by_class"],
        "flow_events_full": flow_full["flow_events"],
        "flow_events_default": flow_default["flow_events"],
        "trace_bytes_logical_full": len(trace_a) if trace_a else 0,
        "trace_byte_identical_across_runs": trace_identical,
        "converged": {a: arms[a]["converged"] for a in arms},
        "acceptance": {
            "floor_pct": FLOOR_PCT,
            # The shipped default must stay under the §14 bar; the
            # full-sampling arm is the audit vehicle, not a shipping
            # config, so its overhead is recorded but not gated.
            "pass": bool(overhead["default"] < FLOOR_PCT
                         and flow_full["audit_ok"]
                         and flow_full["spans"]["in_flight"] == 0
                         and trace_identical
                         and all(a["converged"]
                                 for a in arms.values())),
        },
        "note": "CPU run (tier-1 harness); flow events are host-side "
                "python dicts, so the CPU bound transfers to device "
                "backends.  Negative overhead = the run-to-run noise "
                "floor exceeds the tracker cost.  The audit covers "
                "EVERY emitted span at mod=1: zero leaked / "
                "double-applied after the anti-entropy drain is the "
                "ISSUE-11 conservation acceptance.",
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="perf/flow_r13.json")
    a = ap.parse_args()
    out = run_matrix(smoke=a.smoke, reps=a.reps)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    if not out["acceptance"]["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

"""Sizes VERDICT r3 next #4 (in-kernel run re-merge) on the real traces.

Extends the kernel-exact run simulation with the two candidate in-kernel
merge rules — insert PREPEND-merge (`mutations.rs:84-109`) and tombstone
neighbor-merge (`extend_delete`, `root.rs:9-17`) — and measures peak run
rows.  Result (2026-07-30, full merged streams):

    automerge-paper: base 13218 -> +tomb 12487 (-5.5%); prepend: -0
    rustcode:        base 14878 -> +tomb 12685 (-14.7%); prepend: -0
    sveltecomponent: base  7022 -> +tomb  5868 (-16.4%); prepend: -0

The hypothesized ~2x does NOT exist: run merging requires ORDER
contiguity (the same `can_append` constraint the reference has,
`span.rs:47-53`), and split-induced neighbors are almost never order-
contiguous.  The 2.5x capacity budget is block half-fullness after leaf
splits, which re-merge cannot fix either.  Conclusion: in-kernel
re-merge is a ~1.06x lever on the north star; not worth kernel risk.
Run: python perf/merge_sim.py
"""
import sys; sys.path.insert(0, ".")
from text_crdt_rust_tpu.utils.testdata import flatten_patches, load_testing_data, trace_path

def simulate(patches, merge_prepend=False, merge_tomb=False):
    runs = []  # (order_start, char_len, live)
    next_order = 0
    peak = 0
    def try_merge_at(i):
        # merge runs[i-1] and runs[i] if order-contiguous same-liveness
        if not merge_tomb: return
        if i <= 0 or i >= len(runs): return
        o1, l1, v1 = runs[i-1]; o2, l2, v2 = runs[i]
        if v1 == v2 and o1 + l1 == o2:
            runs[i-1:i+1] = [(o1, l1+l2, v1)]
    for p in patches:
        if p.del_len:
            rem = p.del_len; before = 0; i = 0
            touched = []
            while rem > 0 and i < len(runs):
                o, l, live = runs[i]
                lv = l if live else 0
                cs = min(max(p.pos - before, 0), lv)
                ce = min(max(p.pos + rem - before, 0), lv)
                cov = ce - cs
                if cov > 0:
                    parts = []
                    if cs > 0: parts.append((o, cs, True))
                    parts.append((o + cs, cov, False))
                    if ce < l: parts.append((o + ce, l - ce, True))
                    runs[i:i+1] = parts
                    touched.append(i + (1 if cs > 0 else 0))
                    i += len(parts)
                    rem -= cov
                else:
                    i += 1
                before += lv - cov
            # post-delete: merge tombstones with order-contiguous neighbors
            if merge_tomb:
                # indices shift as we merge; do a simple local pass around touched
                j = 0
                while j < len(runs):
                    o1, l1, v1 = runs[j]
                    if j+1 < len(runs):
                        o2, l2, v2 = runs[j+1]
                        if v1 == v2 and o1 + l1 == o2:
                            runs[j:j+2] = [(o1, l1+l2, v1)]
                            continue
                    j += 1
            next_order += p.del_len
        il = len(p.ins_content)
        if il:
            st = next_order
            if p.pos == 0:
                if merge_prepend and runs and runs[0][2] and st + il == runs[0][0]:
                    runs[0] = (st, il + runs[0][1], True)
                else:
                    runs.insert(0, (st, il, True))
            else:
                before = 0
                for i, (o, l, live) in enumerate(runs):
                    lv = l if live else 0
                    if before + lv >= p.pos:
                        off = p.pos - before
                        if off == l and live and st == o + l:
                            runs[i] = (o, l + il, True)
                        elif off == lv:
                            nxt = runs[i+1] if i+1 < len(runs) else None
                            if merge_prepend and nxt and nxt[2] and st + il == nxt[0]:
                                runs[i+1] = (st, il + nxt[1], True)
                            else:
                                runs.insert(i + 1, (st, il, True))
                        else:
                            runs[i:i+1] = [(o, off, True), (st, il, True), (o + off, l - off, True)]
                        break
                    before += lv
            next_order += il
        peak = max(peak, len(runs))
    return peak, len(runs)

for trace in ("automerge-paper", "rustcode", "sveltecomponent"):
    patches = B.merge_patches(flatten_patches(load_testing_data(trace_path(trace))))
    base = simulate(patches)
    pm = simulate(patches, merge_prepend=True)
    tm = simulate(patches, merge_tomb=True)
    both = simulate(patches, merge_prepend=True, merge_tomb=True)
    print(f"{trace}: base peak/final {base}, +prepend {pm}, +tomb {tm}, +both {both}")

"""Isolate the config-5 per-step regression (r4 1.30ms -> r5 6.07ms).

Times ONE cfg_5-shaped chunk (100 steps x 2048 divergent lanes) on the
attached chip, with the round-5 shared-cum hoist ON (the gate's choice)
and FORCED OFF (the r4 kernel's per-branch cumsum), at the final
capacity 1664 and at a mid-stream growing capacity 1024.

    python perf/cfg5_probe.py
"""
import random
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.utils.testdata import TestPatch


def continue_patches(rng, content, steps, ins_prob=0.45):
    patches = []
    for _ in range(steps):
        if not content or rng.random() < ins_prob:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice("abcdefgh ")
                          for _ in range(rng.randint(1, 4)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, 4), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    return patches, content


def build_cfg5_stacked(n_docs=2048, steps=100):
    """The cfg5-shaped stacked stream (shared with perf/cfg5_sweep.py
    so probe and sweep always tune the SAME workload)."""
    rngs = [random.Random(1000 + d) for d in range(n_docs)]
    contents = [""] * n_docs
    opses = []
    for d in range(n_docs):
        patches, contents[d] = continue_patches(rngs[d], contents[d],
                                                steps)
        ops, _ = B.compile_local_patches(patches, lmax=4, dmax=None)
        opses.append(ops)
    return B.stack_ops(opses)


def main():
    n_docs, steps = 2048, 100
    stacked = build_cfg5_stacked(n_docs, steps)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)

    real_gate = RL._shared_cum_gate
    for cap in (1664, 1024):
        for mode, gate in (("gated", real_gate),
                           ("off", lambda *a: False),
                           ("on", lambda *a: True)):
            RL._shared_cum_gate = gate
            RL._build_call.cache_clear()
            run = RL.make_replayer_lanes(stacked, capacity=cap,
                                         chunk=128)
            np.asarray(run().err)  # compile + warm
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                res = run()
            np.asarray(res.err)
            dt = (time.perf_counter() - t0) / reps
            print(f"cap={cap} shared_cum={mode}: {dt*1e3:.1f}ms/chunk "
                  f"({dt/steps*1e6:.0f}us/step)", flush=True)
    RL._shared_cum_gate = real_gate


if __name__ == "__main__":
    main()

"""ISSUE 13 proof probe: tcrlint gate wall cost + pipeline-aliasing
sanitizer overhead at the 200-doc acceptance shape.

Three measurements, committed as ``perf/lint_sanitize_r15.json``:

1. **lint wall** — one subprocess run of the shared gate entry point
   (``python -m text_crdt_rust_tpu.analysis.lint --json``) over the
   package: must exit 0 and stay under the 10s tier-1 design target;
2. **sanitizer overhead** — same-seed 200-doc × 60-tick × 10%-fault
   loadgen arms (pipeline depth 2) with ``sanitize_pipeline`` off vs
   on, min-of-``--reps`` loop wall each: the on-arm must stay inside
   the PERF.md §14 5% bar;
3. **logical invisibility** — the two arms' logical trace streams must
   be byte-identical (the sanitizer may only *observe*).

Usage: ``python perf/lint_sanitize_probe.py [--docs 200 --ticks 60
--reps 2 --out perf/lint_sanitize_r15.json]``; exits 1 when any claim
fails so the armed silicon chain can gate on it.
"""
import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _one_run(sanitize: bool, a) -> dict:
    cfg = ServeConfig(engine="flat", pipeline_ticks=2,
                      sanitize_pipeline=sanitize, trace_keep=True)
    gen = ServeLoadGen(docs=a.docs, agents_per_doc=3, ticks=a.ticks,
                       events_per_tick=48, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    assert rep["converged"], "probe arm diverged"
    digest = hashlib.sha256(
        gen.server.tracer.logical_bytes()).hexdigest()
    return {"loop_wall_s": rep["device_ticks_wall_s"],
            "wall_s": rep["wall_s"],
            "trace_sha256": digest,
            "sanitize_checks": rep["pipeline"]["sanitize_checks"],
            "overlap_frac": rep["pipeline"]["overlap_frac"]}


def run_arms(a) -> tuple:
    """Min-of-reps per arm, arms INTERLEAVED (off, on, off, on, ...):
    this shared box drifts the same serial workload 11.6-17.0s across
    sessions (PERF.md §17), so back-to-back pairing — not arm blocks —
    is what isolates the sanitizer's own cost."""
    best = {False: None, True: None}
    for _ in range(a.reps):
        for arm in (False, True):
            cur = _one_run(arm, a)
            if (best[arm] is None
                    or cur["loop_wall_s"] < best[arm]["loop_wall_s"]):
                best[arm] = cur
    for arm in best.values():
        arm["reps"] = a.reps
    return best[False], best[True]


def fingerprint_microbench() -> dict:
    """Direct per-call cost of the CRC fingerprint at the serve tick
    shapes — the noise-free number the loop-wall diff approximates."""
    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.serve.batcher import _op_fingerprints

    out = {}
    for bucket in (32, 128):
        stacked = B.stack_ops(
            [B.pad_ops(B.empty_ops(16), bucket) for _ in range(16)])
        t0 = time.perf_counter()
        n = 200
        for _ in range(n):
            _op_fingerprints(stacked)
        out[f"ms_per_check_b{bucket}"] = round(
            (time.perf_counter() - t0) / n * 1e3, 4)
    return out


def run_lint_gate() -> dict:
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    wall = time.perf_counter() - t0
    out = json.loads(r.stdout) if r.stdout.strip() else {}
    return {"rc": r.returncode, "wall_s": round(wall, 3),
            "files": out.get("stats", {}).get("files"),
            "findings": len(out.get("findings", [])),
            "ruff_available": out.get("ruff_available")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--docs", type=int, default=200)
    ap.add_argument("--ticks", type=int, default=60)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default="perf/lint_sanitize_r15.json")
    a = ap.parse_args(argv)

    lint = run_lint_gate()
    off, on = run_arms(a)
    overhead = (on["loop_wall_s"] - off["loop_wall_s"]) / off["loop_wall_s"]
    result = {
        "probe": "lint_sanitize_r15",
        "shape": {"docs": a.docs, "agents": 3, "ticks": a.ticks,
                  "events_per_tick": 48, "fault_rate": 0.10, "seed": 7,
                  "pipeline_ticks": 2, "reps": a.reps},
        "lint": lint,
        "fingerprint_cost": fingerprint_microbench(),
        "sanitize_off": off,
        "sanitize_on": on,
        "sanitize_overhead_frac": round(overhead, 4),
        "byte_identical": on["trace_sha256"] == off["trace_sha256"],
        "claims": {
            "lint_gate_clean": lint["rc"] == 0,
            "lint_under_10s": lint["wall_s"] < 10.0,
            "sanitizer_under_5pct": overhead < 0.05,
            "logical_stream_byte_identical":
                on["trace_sha256"] == off["trace_sha256"],
        },
    }
    ok = all(result["claims"].values())
    result["ok"] = ok
    path = os.path.join(REPO, a.out) if not os.path.isabs(a.out) else a.out
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps(result, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

#!/bin/bash
# Round-8 recovery watcher (ISSUE 5 / ROADMAP #3): kevin's fused
# split-batch prepare has only run on CPU interpret — the 5M silicon
# re-record (engine rle-hbm-fused, W=64, ~78k device steps instead of
# 5M) is the headline this round arms.  Also still pending from r6/r7:
# configs 4/5/5r on-chip (the vectorized YATA scan + blocked lanes
# engines are CPU-proven only) and the serve/serve-lanes/sp rows.
# Each config re-records through the new `--merge-rows` path (single
# config -> BENCH_ALL.json row replacement; no hand-editing, no
# whole-suite resume).
# Safe to re-run; appends to perf/when_up_r8.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r8 watcher)" >> perf/when_up_r8.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r8)" >> perf/when_up_r8.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r8.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r8.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice + rows_per_step SMEM column compile on
# real Mosaic before committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r8.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r8.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r8.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r8.log
# Still-pending r6/r7 rows, most verdict-critical first.
for cfg in 4 5r 5 northstar serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r8.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r8.log
done
echo "$(date -u +%H:%M:%S) r8 re-record done" >> perf/when_up_r8.log

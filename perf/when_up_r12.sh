#!/bin/bash
# Round-12 recovery watcher (ISSUE 12 / ROADMAP #1): supersedes
# when_up_r11.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> serve device smokes ->
# kevin full 5M -> the remaining rows via --merge-rows — then the COST
# LEDGER device re-record.  New in r12: a PIPELINED serve device smoke
# gates the row re-records (the flat backend's double-buffered tick on
# real silicon: async dispatch + the staged sync must hold the
# byte-identical logical contract where device steps actually take
# wall time — this is where the overlap stops being a CPU formality),
# and the re-recorded serve/serve-lanes rows carry the additive
# pipeline_overlap_frac / nagle_txns fields.  Safe to re-run; appends
# to perf/when_up_r12.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r12 watcher)" >> perf/when_up_r12.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r12)" >> perf/when_up_r12.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r12.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r12.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r12.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r12.log; exit 1; }
# Pipelined serve device smoke (new in r12): the double-buffered tick
# on the flat backend, on-device — the staged sync overlapping real
# device steps, convergence + lane bit-identity still green.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  >> perf/when_up_r12.log 2>&1 \
  || { echo "pipelined serve device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r12.log; exit 1; }
# Fused serve-lanes loadgen smoke — the blocked mixed kernel's fused
# splice + the serve stack's fused ticks on device (the lanes backend
# clamps the pipeline to serial; that clamp is part of the smoke).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r12.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r12.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r12.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r12.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter (serve/serve-lanes rows carry
# the additive flow_* provenance + pipeline_overlap_frac/nagle_txns
# fields).
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r12.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r12.log
done
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).  On-chip logical op ages
# must reproduce the re-recorded cpu flow cell (clean-remote p50 4 at
# the small shape) EXACTLY.
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r12.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r12.log
# And prove the cpu contract still holds from this very checkout.
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r12.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r12.log
echo "$(date -u +%H:%M:%S) r12 re-record done" >> perf/when_up_r12.log

"""Step-cost probe for GENERALIZED fused multi-row steps (ISSUE 6
acceptance): the real editing traces (automerge-paper + the northstar
code traces rustcode/sveltecomponent) compiled at EVENT granularity —
the serve shape, one compiled step per patch, where the host coalescer
never runs — then fused by ``ops.batch.fuse_steps``.

Proves, per trace:
- device-step count reduced >= 3x (the acceptance floor) by the fusion
  pass alone, with the per-shape histogram (typing runs / delete sweeps
  / replace pairs / backwards bursts) recorded;
- on a trace PREFIX at CPU-interpret scale, the fused stream is
  bit-identical to the unfused stream AND the flat-engine oracle on
  all four fused-splice surfaces: ``ops.rle`` / ``ops.rle_hbm``
  (expand_runs + the full by-order logs via ``rle_to_flat``) and the
  BLOCKED lanes engines ``ops.rle_lanes`` / ``ops.rle_lanes_mixed``
  (per-lane expansion + the in-kernel by-order origin tables).

Writes ``perf/fused_traces_r9.json``; the silicon re-record of the
fused bench rows is armed in ``perf/when_up_r9.sh``.

Run: python perf/fused_trace_probe.py [--identity-patches 1200]
     [--fuse-w 8] [--smoke]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke):
    #       the caller already pinned the platform

import numpy as np  # noqa: E402

from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import flat as F  # noqa: E402
from text_crdt_rust_tpu.ops import rle as R  # noqa: E402
from text_crdt_rust_tpu.ops import rle_hbm as RH  # noqa: E402
from text_crdt_rust_tpu.ops import rle_lanes as RL  # noqa: E402
from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM  # noqa: E402
from text_crdt_rust_tpu.ops import span_arrays as SA  # noqa: E402
from text_crdt_rust_tpu.utils.testdata import (  # noqa: E402
    flatten_patches,
    load_testing_data,
    trace_path,
)

TRACES = ("automerge-paper", "rustcode", "sveltecomponent")
LMAX = 256          # merged-run cap (bench lmax_cap scale; typing runs
#                     in the code traces coalesce past 64 chars)
FLOOR_X = 3.0


def full_trace_cut(name: str, fuse_w: int):
    """Event-granularity compile of the WHOLE trace + one fusion pass
    (host arithmetic — the exact device-step counts, no replay)."""
    patches = flatten_patches(load_testing_data(trace_path(name)))
    t0 = time.perf_counter()
    ops_u, _ = B.compile_local_patches(patches, lmax=LMAX, dmax=None)
    ops_f, st = B.fuse_steps(ops_u, fuse_w=fuse_w)
    assert B.fused_width(ops_f) <= fuse_w
    return {
        "trace": name,
        "patches": len(patches),
        "steps_unfused": st.steps_in,
        "steps_fused": st.steps_out,
        "step_reduction_x": round(st.reduction_x, 2),
        "fuse_shapes": dict(st.fused),
        "compile_wall_s": round(time.perf_counter() - t0, 2),
    }


def expand_signed(res, b=0):
    """Un-blocked lanes plane -> per-char signed order sequence."""
    o = np.asarray(res.ordp)[:, b]
    ln = np.asarray(res.lenp)[:, b]
    out = []
    for oo, ll in zip(o, ln):
        if oo == 0:
            continue
        s = abs(int(oo)) - 1
        out.extend((np.sign(int(oo))
                    * (s + np.arange(int(ll)) + 1)).tolist())
    return out


def blocked_mixed_signed(res, b=0):
    """Blocked mixed state -> per-char signed order sequence."""
    ordp = np.asarray(res.ordp)[:, b]
    lenp = np.asarray(res.lenp)[:, b]
    nlog = int(np.asarray(res.nlog)[0, b])
    blk = np.asarray(res.blkord)[:, b]
    rws = np.asarray(res.rws)[:, b]
    K = res.block_k
    out = []
    for sl in range(nlog):
        bb, r = int(blk[sl]), int(rws[sl])
        for oo, ll in zip(ordp[bb * K: bb * K + r],
                          lenp[bb * K: bb * K + r]):
            if oo == 0:
                continue
            s = abs(int(oo)) - 1
            out.extend((np.sign(int(oo))
                        * (s + np.arange(int(ll)) + 1)).tolist())
    return out


def _bounded_prefix(patches, n_patches: int, char_budget: int):
    """Interpret-feasible prefix of a real trace: total inserted chars
    bounded (interpret wall scales with the state plane).  A trace that
    OPENS with an oversized paste (rustcode: one 42k-char paste — no
    literal prefix is feasible) is rebased instead: the edits after the
    paste are cursor-localized, so a synthetic base insert covering
    exactly the touched window stands in for the paste and every edit
    shifts into it — offsets, delete spans and the shape mix are
    preserved verbatim.  Edits left referencing out-of-range content
    are dropped (count returned); the result is a valid standalone
    edit history."""
    from text_crdt_rust_tpu.utils.testdata import TestPatch

    if patches and len(patches[0].ins_content) > char_budget:
        return _windowed_prefix(patches, n_patches, char_budget)
    out, live, total_ins, dropped = [], 0, 0, 0
    for p in patches[:n_patches]:
        ins = p.ins_content
        if len(ins) > char_budget // 2:
            ins = ins[:char_budget // 2]
        if p.pos > live or p.pos + p.del_len > live:
            dropped += 1
            continue
        out.append(TestPatch(p.pos, p.del_len, ins))
        live += len(ins) - p.del_len
        total_ins += len(ins)
        if total_ins > char_budget:
            break
    return out, dropped


def _windowed_prefix(patches, n_patches: int, char_budget: int):
    """Rebase a giant-opening-paste trace onto the touched window (see
    ``_bounded_prefix``): pass 1 grows the window [lo, hi) over the
    maximal run of post-paste edits staying inside the budget; pass 2
    replays them shifted by -lo over a synthetic base insert of the
    window's real pasted content."""
    from text_crdt_rust_tpu.utils.testdata import TestPatch

    lo = hi = None
    kept = []
    for p in patches[1:n_patches]:
        nlo = p.pos if lo is None else min(lo, p.pos)
        nhi = (p.pos + p.del_len if hi is None
               else max(hi, p.pos + p.del_len))
        if nhi - nlo > char_budget:
            break
        lo, hi = nlo, nhi
        kept.append(p)
    if lo is None:
        return [patches[0]], 0
    span = hi - lo
    base = patches[0].ins_content[lo:hi].ljust(span, "x")
    out, live, dropped = [TestPatch(0, 0, base)], span, 0
    for p in kept:
        sp = p.pos - lo
        if sp < 0 or sp + p.del_len > live:
            dropped += 1
            continue
        out.append(TestPatch(sp, p.del_len, p.ins_content))
        live += len(p.ins_content) - p.del_len
    return out, dropped


def identity_prefix(name: str, n_patches: int, fuse_w: int,
                    char_budget: int = 2500, chunk: int = 128):
    """Replay a (bounded) trace prefix fused vs unfused through every
    fused-splice surface on CPU interpret; all comparisons bit-exact.
    ``chunk`` pads the step axis (interpret wall scales with padded
    steps — the smoke path shrinks it)."""
    patches, dropped = _bounded_prefix(
        flatten_patches(load_testing_data(trace_path(name))),
        n_patches, char_budget)
    lmax = 64
    ops_u, no_u = B.compile_local_patches(patches, lmax=lmax, dmax=None)
    fused, st = B.fuse_steps(ops_u, fuse_w=fuse_w)
    assert no_u == int(np.asarray(
        fused.order_advance, dtype=np.int64).sum())
    chars = no_u
    t0 = time.perf_counter()

    # Oracle: the flat engine on the UNFUSED stream.
    ref = F.apply_ops(SA.make_flat_doc(2 * chars + lmax), ops_u)
    want_spans = SA.doc_spans(ref)

    block_k = 64
    cap = ((int(chars * 2.1) + block_k - 1) // block_k) * block_k
    kw = dict(capacity=cap, batch=8, block_k=block_k, chunk=chunk,
              interpret=True)
    verdicts = {}

    # rle + rle_hbm: expand_runs + full by-order logs.
    for ename, mk in (("rle", R.replay_local_rle),
                      ("rle-hbm", RH.replay_local_rle_hbm)):
        res_u = mk(ops_u, **kw)
        res_f = mk(fused, **kw)
        same = np.array_equal(R.expand_runs(res_u), R.expand_runs(res_f))
        du = R.rle_to_flat(ops_u, res_u, capacity=2 * chars + lmax)
        df = R.rle_to_flat(fused, res_f, capacity=2 * chars + lmax)
        logs = all(
            np.array_equal(np.asarray(getattr(du, fld)),
                           np.asarray(getattr(df, fld)))
            for fld in ("signed", "ol_log", "or_log", "rank_log",
                        "chars_log", "n", "next_order"))
        verdicts[ename] = bool(
            same and logs and SA.doc_spans(df) == want_spans)

    # Blocked lanes engines ([S, B] streams, 2 lanes).
    smax = ((max(ops_u.num_steps, fused.num_steps) + chunk - 1)
            // chunk) * chunk
    su = B.stack_ops([B.pad_ops(ops_u, smax)] * 2)
    sf = B.stack_ops([B.pad_ops(fused, smax)] * 2)
    lkw = dict(capacity=cap, block_k=block_k, chunk=chunk, interpret=True)
    ru = RL.make_replayer_lanes_blocked(su, **lkw)()
    rf = RL.make_replayer_lanes_blocked(sf, **lkw)()
    ru.check()
    rf.check()
    verdicts["rle-lanes-blocked"] = bool(np.array_equal(
        RL.expand_lane_blocked(ru, 0), RL.expand_lane_blocked(rf, 0)))

    mu = RLM.replay_lanes_mixed_blocked(su, **lkw)
    mf = RLM.replay_lanes_mixed_blocked(sf, **lkw)
    mu.check()
    mf.check()
    verdicts["rle-lanes-mixed-blocked"] = bool(
        blocked_mixed_signed(mu) == blocked_mixed_signed(mf)
        and np.array_equal(np.asarray(mu.oll), np.asarray(mf.oll))
        and np.array_equal(np.asarray(mu.orl), np.asarray(mf.orl)))

    return {
        "trace": name,
        "identity_patches": len(patches),
        "patches_dropped_out_of_range": dropped,
        "steps_unfused": st.steps_in,
        "steps_fused": st.steps_out,
        "prefix_reduction_x": round(st.reduction_x, 2),
        "bit_identical": verdicts,
        "oracle_equal": all(verdicts.values()),
        "interpret_wall_s": round(time.perf_counter() - t0, 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--identity-patches", type=int, default=400)
    ap.add_argument("--fuse-w", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, automerge only (the tier-1 smoke "
                         "path, tests/test_fused_trace_probe.py)")
    ap.add_argument("--out", default="perf/fused_traces_r9.json")
    args = ap.parse_args()
    traces = TRACES[:1] if args.smoke else TRACES
    n_id = min(args.identity_patches, 200) if args.smoke \
        else args.identity_patches

    cuts = [full_trace_cut(t, args.fuse_w) for t in traces] \
        if not args.smoke else []
    idents = [identity_prefix(t, n_id, args.fuse_w,
                              chunk=64 if args.smoke else 128)
              for t in traces]

    out = {
        "workload": {
            "granularity": "event (one compiled step per patch — the "
                           "serve-batcher shape; the host coalescer "
                           "never runs on per-event streams)",
            "lmax": LMAX, "fuse_w": args.fuse_w, "smoke": args.smoke,
        },
        "full_trace_step_cut": cuts,
        "bit_identity_prefix": idents,
        "acceptance": {
            "floor_x": FLOOR_X,
            "measured_x": (min(c["step_reduction_x"] for c in cuts)
                           if cuts else
                           min(i["prefix_reduction_x"] for i in idents)),
            "bit_identical_all": all(i["oracle_equal"] for i in idents),
            "pass": (all(c["step_reduction_x"] >= FLOOR_X for c in cuts)
                     if cuts else True)
            and all(i["oracle_equal"] for i in idents),
        },
    }
    if not args.smoke:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)
    print(json.dumps(out))
    print(f"acceptance {'PASS' if out['acceptance']['pass'] else 'FAIL'}",
          file=sys.stderr)
    return 0 if out["acceptance"]["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Kernel-exact step-cost probe for the BLOCKED lanes engines (ISSUE 2):
touched rows per step, before vs after, on the config-5 and config-5r
workloads — the on-CPU evidence the PR lands while the TPU tunnel is
down (`perf/when_up_r6.sh` re-records the real configs on recovery).

Modeled on perf/merge_sim.py: a host replay of the kernels' EXACT row
algebra (runs, K-row blocks, logical block tables, leaf splits, the
order->block hint with cold/stale fallbacks) over the same workload
generators and growing per-chunk capacities bench.py uses, counting two
metrics per step:

- **touched rows** — unique state/table rows the step's algorithm
  examines or writes: the un-blocked kernels' position->run scan,
  splice, and interval clip each span the whole allocated [CAP, B]
  plane, so an un-blocked step touches CAP rows; a blocked step touches
  the NBT-row logical table + the K-row target block (+ K+NBT per extra
  delete block / split / hint fallback).  This is the O(NB+K)-vs-O(CAP)
  claim the restructure makes, and the acceptance metric (>= 10x).
- **pass traffic** — row-reads summed over every vector pass the kernel
  actually makes, including the un-blocked cumsum's log2(CAP) rolls and
  the blocked kernels' NB-way select-chain gathers (which stream CAP
  rows to address one block).  This is the honest wall-clock predictor:
  smaller than the touched-rows ratio because lane-addressed gathers
  still stream the plane; the chip run decides the final number.

Single-author remote streams (the 5r shape) integrate with a
first-probe YATA break (each physically-following char either IS the
op's origin_right or has an earlier-positioned origin_left), so the
scan cost is one probe — the same count the kernels pay on these
streams.

Round 7 (ISSUE 4) adds ``--serve``: replay the SERVE loadgen tick trace
— the per-doc compiled streams the continuous batcher actually ships to
the device, tapped via ``ContinuousBatcher.step_trace``, with per-lane
sims re-seeded from the oracle at every residency upload exactly as
``serve/lanes_backend.upload_lane`` re-seeds the device — through the
same two cost models, plus the live acceptance proof: the
``rle-lanes-mixed`` loadgen run must end bit-identical per doc to a
``flat``-backend twin run of the same seed AND to the host oracles.
Writes ``perf/serve_lanes_r7.json`` and prints one bench-row-ready JSON
line (bench.py's ``serve-lanes`` config wraps it).

Run: python perf/blocked_lanes_sim.py [--docs N] [--block-k K] [--serve]
"""
import argparse
import json
import math
import random
import sys
import time

sys.path.insert(0, ".")

from text_crdt_rust_tpu.config import lane_block_geometry  # noqa: E402
from text_crdt_rust_tpu.ops.batch import row_growth_bound  # noqa: E402


class Counter:
    def __init__(self):
        self.unb_touched = 0
        self.unb_traffic = 0
        self.blk_touched = 0
        self.blk_traffic = 0
        self.steps = 0
        self.splits = 0
        self.hint_misses = 0
        self.hint_probes = 0


class UnblockedCost:
    """Pass counts of the un-blocked kernels (rle_lanes /
    rle_lanes_mixed): every phase spans the allocated [CAP] plane."""

    def __init__(self, cap):
        self.cap = cap
        self.logc = max(1, math.ceil(math.log2(max(cap, 2))))

    def local_insert(self, c: Counter):
        c.unb_touched += self.cap
        # live prefix (1 + log2 rolls) + locate reduces (5) + splice (8)
        c.unb_traffic += (self.logc + 14) * self.cap

    def local_delete(self, c: Counter):
        c.unb_touched += self.cap
        # live prefix + clip + two apply_partial transforms
        c.unb_traffic += (self.logc + 19) * self.cap

    def remote_insert(self, c: Counter, ocap):
        c.unb_touched += self.cap + 3  # 3 indexed by-order entries
        # hoisted raw cumsum + cursor_after (3) + 1 scan probe
        # (3 t_reads over OCAP + cursor_after 3 + run_at 3) + splice 13
        c.unb_traffic += (self.logc + 22) * self.cap + 3 * ocap

    def remote_delete(self, c: Counter):
        c.unb_touched += self.cap
        # interval clip + per-slot updates + two apply_partials
        c.unb_traffic += 24 * self.cap


class BlockedLaneSim:
    """One lane's EXACT blocked-kernel row algebra: K-row physical
    blocks, logical block order, leaf splits, liv/raw tables, and the
    order->block hint with cold/stale fallback accounting."""

    def __init__(self, K, cap, counter, ocap=0):
        self.K = K
        self.cap = cap
        self.ocap = ocap
        self.c = counter
        self.nbt = max(8, cap // K)
        # physical blocks: list of lists of [start_order, length, live]
        self.blocks = [[]]
        self.order = [0]      # logical slot -> physical block
        self.hint = {}        # order -> physical block (may be stale)
        self.fwd = {}         # block -> split destination (last)
        self._sb = set()      # per-step: distinct blocks touched
        self._st = False      # per-step: logical tables examined
        self._sf = 0          # per-step: whole-plane fallbacks
        self._se = 0          # per-step: indexed table entries read

    def begin_step(self):
        self._sb = set()
        self._st = False
        self._sf = 0
        self._se = 0

    def end_step(self):
        """UNIQUE rows examined this step: each distinct block once,
        the logical tables once, each plane-scan fallback, each indexed
        table entry."""
        self.c.blk_touched += (self.K * len(self._sb)
                               + (self.nbt if self._st else 0)
                               + self.cap * self._sf + self._se)

    def grow(self, cap, ocap=0):
        self.cap = cap
        self.ocap = ocap
        self.nbt = max(8, cap // self.K)
        # hints PERSIST across chunks (the kernel carries ordblk in the
        # warm-start state tuple)

    # -- bookkeeping ------------------------------------------------------

    def _runs(self):
        for b in self.order:
            for r in self.blocks[b]:
                yield r

    def _locate_order(self, o):
        """Hint-guided order locate: (block, run) + cost accounting."""
        self.c.hint_probes += 1
        self.c.blk_traffic += 2 * self.K + self.ocap  # verify + hint read
        self._se += 1
        hb = self.hint.get(o)
        if hb is not None and hb < len(self.blocks):
            for r in self.blocks[hb]:
                if r[0] <= o < r[0] + r[1]:
                    self._sb.add(hb)
                    return hb, r
        # stale hint: chase up to two split forward pointers (one K-row
        # verify each) before the plane-scan fallback
        cand = hb
        for _hop in range(2):
            cand = self.fwd.get(cand) if cand is not None else None
            if cand is None or cand >= len(self.blocks):
                break
            self.c.blk_traffic += 2 * self.K
            for r in self.blocks[cand]:
                if r[0] <= o < r[0] + r[1]:
                    self._sb.add(cand)
                    for oo in range(r[0], r[0] + r[1]):
                        self.hint[oo] = cand
                    return cand, r
        # fallback: whole-plane scan + heal the whole found RUN's span
        self.c.hint_misses += 1
        self._sf += 1
        self.c.blk_traffic += self.cap
        for b in self.order:
            for r in self.blocks[b]:
                if r[0] <= o < r[0] + r[1]:
                    for oo in range(r[0], r[0] + r[1]):
                        self.hint[oo] = b
                    return b, r
        raise AssertionError(f"order {o} absent")

    def _slot_of_live(self, rank1):
        self._st = True
        self.c.blk_traffic += self.nbt
        before = 0
        for li, b in enumerate(self.order):
            lv = sum(r[1] for r in self.blocks[b] if r[2])
            if before + lv >= rank1:
                return li, before
            before += lv
        return len(self.order) - 1, before - lv

    def _slot_of_raw(self, rank1):
        self._st = True
        self.c.blk_traffic += self.nbt
        before = 0
        for li, b in enumerate(self.order):
            rw = sum(r[1] for r in self.blocks[b])
            if before + rw >= rank1:
                return li, before
            before += rw
        return len(self.order) - 1, before - rw

    def _maybe_split(self, li, w=1):
        """Returns True when a split fired (the kernel re-descends
        under ``lax.cond`` only then).  ``w`` > 1 is a fused W-row
        splice needing W + 1 rows of headroom (the kernel's
        ``r0 + w + 1 > K`` check)."""
        b = self.order[li]
        if len(self.blocks[b]) + w + 1 <= self.K:
            return False
        assert len(self.blocks) < self.cap // self.K, "out of blocks"
        rows = self.blocks[b]
        keep = len(rows) // 2
        nb = len(self.blocks)
        self.blocks.append(rows[keep:])
        self.blocks[b] = rows[:keep]
        self.order.insert(li + 1, nb)
        # moved rows' hints go stale (NOT updated — kernel heals on
        # probe); cost: gather + two scatters + table shift
        self.c.splits += 1
        self.fwd[b] = nb
        self._sb.add(b)
        self._sb.add(nb)
        self._st = True
        self.c.blk_traffic += 4 * self.cap + self.nbt
        return True

    def _block_cost(self, b):
        """One gathered-block locate + splice of block ``b``."""
        self._sb.add(b)
        # gather x2 + in-block cumsum/splice (~log2 K + 10 K-passes)
        # + scatter x2 (each streams the plane in the select chain)
        self.c.blk_traffic += 4 * self.cap + \
            (math.ceil(math.log2(self.K)) + 10) * self.K

    # -- ops --------------------------------------------------------------

    def insert_local(self, pos, il, st, w=1):
        """``w`` > 1 is a FUSED backwards-burst step: W stride-L runs
        (descending orders in doc order) land in ONE splice — same
        one-block cost, W + 1 rows of split headroom, merge w==1-only
        (the kernels' contract)."""
        li, before = self._slot_of_live(pos) if pos else (0, 0)
        if self._maybe_split(li, w):
            li, before = self._slot_of_live(pos) if pos else (0, 0)
        b = self.order[li]
        self._block_cost(b)
        rows = self.blocks[b]
        local = pos - before
        L = il // w
        new = [[st + il - (j + 1) * L, L, True] for j in range(w)]
        if pos == 0:
            rows[0:0] = new
        else:
            at = 0
            for i, r in enumerate(rows):
                lv = r[1] if r[2] else 0
                if at + lv >= local:
                    off_live = local - at
                    # char offset of the off_live-th live char's end
                    off = off_live
                    if (w == 1 and r[2] and off == r[1]
                            and st == r[0] + r[1]):
                        r[1] += il
                    elif off == r[1]:
                        rows[i + 1: i + 1] = new
                    elif off < r[1]:
                        tail = [r[0] + off, r[1] - off, r[2]]
                        rows[i: i + 1] = [[r[0], off, r[2]],
                                          *new, tail]
                    break
                at += lv
        for o in range(st, st + il):
            self.hint[o] = b

    def delete_local(self, pos, d):
        rem = d
        while rem > 0:
            li, before = self._slot_of_live(pos + 1)
            if self._maybe_split(li):
                li, before = self._slot_of_live(pos + 1)
            b = self.order[li]
            self._block_cost(b)
            rows = self.blocks[b]
            # One block pass mirrors the kernel exactly: pre-delete
            # cumsums, ``rem`` held fixed for the whole pass.
            covered = 0
            out = []
            at = before
            for r in rows:
                lv = r[1] if r[2] else 0
                cs = min(max(pos - at, 0), lv)
                ce = min(max(pos + rem - at, 0), lv)
                cov = ce - cs
                if cov > 0:
                    if cs > 0:
                        out.append([r[0], cs, True])
                    out.append([r[0] + cs, cov, False])
                    if ce < r[1]:
                        out.append([r[0] + ce, r[1] - ce, True])
                    covered += cov
                else:
                    out.append(r)
                at += lv
            self.blocks[b] = out
            if covered == 0:
                raise AssertionError("delete past end")
            rem -= covered

    def remote_insert(self, o_left, il, st):
        # cursor_after: hint locate + slot inverse + in-block prefix
        if o_left is not None:
            hb, r = self._locate_order(o_left)
            self._st = True
            self.c.blk_traffic += self.nbt + self.K
            # raw position of o_left + 1
            raw = 0
            for b in self.order:
                if b == hb:
                    break
                raw += sum(x[1] for x in self.blocks[b])
            for x in self.blocks[hb]:
                if x is r:
                    break
                raw += x[1]
            cursor = raw + (o_left - r[0]) + 1
        else:
            cursor = 0
        # one YATA probe (first-probe break on single-author streams):
        # run_at_raw descent+gather + 3 table reads + cursor_after of
        # the probed char's origin_left (its block joins the step set)
        self._st = True
        self._se += 4
        raw_at = 0
        for pb in self.order:
            w = sum(x[1] for x in self.blocks[pb])
            if raw_at + w > cursor:
                self._sb.add(pb)
                break
            raw_at += w
        self.c.blk_traffic += self.nbt + 3 * self.K + 3 * self.ocap \
            + self.nbt
        # splice at raw cursor
        li, before = self._slot_of_raw(cursor) if cursor else (0, 0)
        if self._maybe_split(li):
            li, before = self._slot_of_raw(cursor) if cursor else (0, 0)
        b = self.order[li]
        self._block_cost(b)
        rows = self.blocks[b]
        local = cursor - before
        if cursor == 0:
            rows.insert(0, [st, il, True])
        else:
            at = 0
            for i, r in enumerate(rows):
                if at + r[1] >= local:
                    off = local - at
                    if (r[2] and off == r[1] and st == r[0] + r[1]
                            and o_left == r[0] + r[1] - 1):
                        r[1] += il
                    elif off == r[1]:
                        rows.insert(i + 1, [st, il, True])
                    else:
                        tail = [r[0] + off, r[1] - off, r[2]]
                        rows[i: i + 1] = [[r[0], off, r[2]],
                                          [st, il, True], tail]
                    break
                at += r[1]
        for o in range(st, st + il):
            self.hint[o] = b

    def remote_delete(self, t, d):
        o = t
        end = t + d
        while o < end:
            hb, r = self._locate_order(o)
            li = self.order.index(hb)
            self._st = True
            self.c.blk_traffic += self.nbt
            aa = o - r[0]
            ee = min(r[1], end - r[0])
            cov = ee - aa
            if r[2]:
                if aa == 0 and ee == r[1]:
                    r[2] = False
                    self._sb.add(hb)
                    self.c.blk_traffic += 2 * self.cap + self.K
                else:
                    if self._maybe_split(li):
                        hb, r = self._locate_order(o)
                    rows = self.blocks[hb]
                    i = rows.index(r)
                    parts = []
                    if aa > 0:
                        parts.append([r[0], aa, True])
                    parts.append([r[0] + aa, cov, False])
                    if ee < r[1]:
                        parts.append([r[0] + ee, r[1] - ee, True])
                    rows[i: i + 1] = parts
                    self._block_cost(hb)
            o = r[0] + ee


def config5_workload(docs, chunks, steps_per_chunk, block_k, remote):
    """Replay the bench config-5/5r workload shape through both cost
    models (same generators and growing capacities as bench.py)."""
    from bench import _PeerSynth, _continue_patches
    from text_crdt_rust_tpu.ops import batch as B

    c = Counter()
    rngs = [random.Random((7000 if remote else 1000) + d)
            for d in range(docs)]
    contents = [""] * docs
    synths = [_PeerSynth(f"peer{d}") for d in range(docs)]
    tables = [B.AgentTable([f"peer{d}"]) for d in range(docs)]
    assigners = [None] * docs
    sims = [None] * docs
    caps = []
    cum_steps = 0
    for ci in range(chunks):
        chunk_ops = []
        for d in range(docs):
            patches, contents[d] = _continue_patches(
                rngs[d], contents[d], steps_per_chunk, ins_prob=0.45)
            if remote:
                txns = synths[d].apply(patches)
                ops, assigners[d] = B.compile_remote_txns(
                    txns, tables[d], assigner=assigners[d], lmax=4,
                    dmax=None)
            else:
                start = assigners[d] or 0
                ops, assigners[d] = B.compile_local_patches(
                    patches, lmax=4, dmax=None, start_order=start)
            chunk_ops.append(ops)
        cum_steps += max(o.num_steps for o in chunk_ops)
        cap = max(lane_block_geometry(row_growth_bound(cum_steps),
                                      block_k)[0], 4 * block_k)
        caps.append(cap)
        unb = UnblockedCost(cap)
        for d, ops in enumerate(chunk_ops):
            ocap = 4 * steps_per_chunk * (ci + 1) + 4
            if sims[d] is None:
                sims[d] = BlockedLaneSim(block_k, cap, c, ocap)
            else:
                sims[d].grow(cap, ocap)
            sim = sims[d]
            import numpy as np
            kind = np.asarray(ops.kind)
            pos = np.asarray(ops.pos)
            dln = np.asarray(ops.del_len)
            dtg = np.asarray(ops.del_target)
            olp = np.asarray(ops.origin_left).astype(np.int64)
            iln = np.asarray(ops.ins_len)
            stt = np.asarray(ops.ins_order_start)
            for s in range(ops.num_steps):
                k, p, dl, il = (int(kind[s]), int(pos[s]), int(dln[s]),
                                int(iln[s]))
                st = int(stt[s])
                if k == 0 and dl:
                    c.steps += 1
                    unb.local_delete(c)
                    sim.begin_step()
                    sim.delete_local(p, dl)
                    sim.end_step()
                if k == 0 and il:
                    c.steps += 1
                    unb.local_insert(c)
                    sim.begin_step()
                    sim.insert_local(p, il, st)
                    sim.end_step()
                if k == 1 and il:
                    c.steps += 1
                    unb.remote_insert(c, sim.ocap)
                    ol = None if olp[s] == 0xFFFFFFFF else int(olp[s])
                    sim.begin_step()
                    sim.remote_insert(ol, il, st)
                    sim.end_step()
                if k == 2 and dl:
                    c.steps += 1
                    unb.remote_delete(c)
                    sim.begin_step()
                    sim.remote_delete(int(dtg[s]), dl)
                    sim.end_step()
    return c, caps


def _seed_sim_from_oracle(sim: BlockedLaneSim, oracle) -> None:
    """Re-seed a lane sim from a host oracle the way
    ``serve/lanes_backend.upload_lane`` seeds the device: the SAME
    packer call (``pack_lane_blocks`` owns the occupancy rule), its
    run->block assignment expanded into the sim's block lists and warm
    hints, forward pointers cleared."""
    from text_crdt_rust_tpu.ops.lane_blocks import (
        oracle_runs,
        pack_lane_blocks,
    )

    starts, lens = oracle_runs(oracle)
    nb = sim.cap // sim.K
    _, run_block = pack_lane_blocks(starts, lens, K=sim.K, NB=nb,
                                    NBT=max(8, nb), capacity=sim.cap)
    nblocks = max(int(run_block[-1]) + 1, 1) if len(run_block) else 1
    sim.blocks = [[] for _ in range(nblocks)]
    sim.order = list(range(nblocks))
    sim.hint = {}
    sim.fwd = {}
    for s, ln, b in zip(starts, lens, run_block):
        o0 = int(abs(s)) - 1
        sim.blocks[int(b)].append([o0, int(ln), bool(s > 0)])
        for oo in range(o0, o0 + int(ln)):
            sim.hint[oo] = int(b)


def _replay_stream(sim: BlockedLaneSim, unb: UnblockedCost, c: Counter,
                   ops) -> None:
    """One per-doc compiled tick stream through both cost models (the
    config5_workload inner loop, unbatched [S] columns)."""
    import numpy as np

    kind = np.asarray(ops.kind)
    pos = np.asarray(ops.pos)
    dln = np.asarray(ops.del_len)
    dtg = np.asarray(ops.del_target)
    olp = np.asarray(ops.origin_left).astype(np.int64)
    iln = np.asarray(ops.ins_len)
    stt = np.asarray(ops.ins_order_start)
    wcol = np.maximum(np.asarray(ops.rows_per_step), 1)
    for s in range(ops.num_steps):
        k, p, dl, il = (int(kind[s]), int(pos[s]), int(dln[s]),
                        int(iln[s]))
        st = int(stt[s])
        if k == 0 and dl:
            c.steps += 1
            unb.local_delete(c)
            sim.begin_step(); sim.delete_local(p, dl); sim.end_step()
        if k == 0 and il:
            c.steps += 1
            unb.local_insert(c)
            sim.begin_step()
            sim.insert_local(p, il, st, int(wcol[s]))
            sim.end_step()
        if k == 1 and il:
            c.steps += 1
            unb.remote_insert(c, sim.ocap)
            ol = None if olp[s] == 0xFFFFFFFF else int(olp[s])
            sim.begin_step(); sim.remote_insert(ol, il, st); sim.end_step()
        if k == 2 and dl:
            c.steps += 1
            unb.remote_delete(c)
            sim.begin_step(); sim.remote_delete(int(dtg[s]), dl); sim.end_step()


def serve_workload(smoke: bool = False, block_k: int = 0,
                   engines=("rle-lanes-mixed", "flat")):
    """The ISSUE-4 acceptance + perf probe: run the seeded serve
    loadgen on BOTH lane backends (bit-identity proof), replaying the
    lanes run's tick trace through the kernel-exact blocked cost model
    and the flat engine's whole-[CAP]-plane-per-step model.

    The flat serve engine (`ops/flat.py`) splices the whole [CAP] char
    plane per step exactly like the un-blocked lanes kernels splice
    their [CAP] run plane, so ``UnblockedCost`` doubles as its
    touched-rows model (CAP = the serve lane capacity).  Both models
    assume shallow YATA scans (serve edits are small and conflicts
    rare); splice/locate/split costs are kernel-exact.

    ``block_k`` overrides ``ServeConfig.lanes_block_k`` (the --sweep-k
    driver); ``engines`` narrows the run (the sweep skips the flat twin
    — it is K-independent — and leans on the loadgen's built-in
    always-resident oracle twin for convergence).
    """
    from text_crdt_rust_tpu.config import ServeConfig, lane_block_geometry
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    docs, ticks, events = (24, 10, 16) if smoke else (200, 60, 48)
    base = ServeConfig()
    K = block_k or base.lanes_block_k
    cap_runs, NB, NBT = lane_block_geometry(base.lane_capacity, K)
    OCAP = base.order_capacity
    c = Counter()
    unb = UnblockedCost(base.lane_capacity)
    sims = {}
    reports = {}
    strings = {}
    shapes = None

    for engine in engines:
        scfg = ServeConfig(engine=engine, num_shards=2,
                           lanes_per_shard=16, lanes_block_k=K)
        gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                           events_per_tick=events, zipf_alpha=1.1,
                           fault_rate=0.10, local_prob=0.25, seed=7,
                           cfg=scfg)
        if engine == "rle-lanes-mixed":
            # Tap every compiled per-doc tick stream; re-seed the doc's
            # sim at every residency upload (the device does the same).
            res = gen.server.residency

            def trace(doc_id, ops):
                sim = sims.get(doc_id)
                if sim is None:
                    sim = sims[doc_id] = BlockedLaneSim(
                        K, cap_runs, c, OCAP)
                _replay_stream(sim, unb, c, ops)

            gen.server.batcher.step_trace = trace
            for si, backend in enumerate(res.backends):
                def wrap(orig, si):
                    def upload(b, oracle, ranks):
                        doc_id = res.lane_owner[si][b]
                        sim = sims.get(doc_id)
                        if sim is None:
                            sim = sims[doc_id] = BlockedLaneSim(
                                K, cap_runs, c, OCAP)
                        _seed_sim_from_oracle(sim, oracle)
                        orig(b, oracle, ranks)
                    return upload
                backend.upload_lane = wrap(backend.upload_lane, si)
        t0 = time.perf_counter()
        report = gen.run()
        report["probe_wall_s"] = round(time.perf_counter() - t0, 3)
        assert report["converged"], (engine, report["mismatches"][:4])
        reports[engine] = report
        strings[engine] = {w.doc_id: gen.server.doc_string(w.doc_id)
                           for w in gen.worlds}
        if engine == "rle-lanes-mixed":
            shapes = sorted(set().union(
                *(b.shapes_seen
                  for b in gen.server.residency.backends)))

    bit_identical = (strings["rle-lanes-mixed"] == strings["flat"]
                     if "flat" in strings else None)
    tr = c.unb_touched / max(c.blk_touched, 1)
    pr = c.unb_traffic / max(c.blk_traffic, 1)
    out = {
        "workload": {
            "docs": docs, "agents_per_doc": 3, "ticks": ticks,
            "events_per_tick": events, "fault_rate": 0.10,
            "zipf_alpha": 1.1, "seed": 7,
            "num_shards": 2, "lanes_per_shard": 16,
            "lane_capacity": base.lane_capacity,
            "block_k": K, "NB": NB, "NBT": NBT,
            "order_capacity": OCAP,
        },
        "bit_identical_flat_vs_lanes": bit_identical,
        "trace_steps": c.steps,
        "splits": c.splits,
        "hint_misses": c.hint_misses,
        "hint_probes": c.hint_probes,
        "touched_rows_per_step": {
            "flat": round(c.unb_touched / max(c.steps, 1), 1),
            "lanes_blocked": round(c.blk_touched / max(c.steps, 1), 1),
            "ratio": round(tr, 2),
        },
        "pass_traffic_per_step": {
            "flat": round(c.unb_traffic / max(c.steps, 1), 1),
            "lanes_blocked": round(c.blk_traffic / max(c.steps, 1), 1),
            "ratio": round(pr, 2),
        },
        "lanes_shapes_seen": shapes,
        "per_engine": {
            eng: {
                "converged": r["converged"],
                "item_ops_applied": r["item_ops_applied"],
                "device_steps": r["server"].get("device_steps", 0),
                "device_ticks_wall_s": r["device_ticks_wall_s"],
                "tick_ms": r["tick_ms"],
                "latency_us": r["latency_us"],
                "evictions": r["server"].get("evictions", 0),
                "restores": r["server"].get("restores", 0),
                "docs_degraded": r["server"].get("docs_degraded", 0),
                # ISSUE 7: the lanes backend serves the columnar wire +
                # delta checkpoints (ServeConfig defaults) — byte
                # counters prove the evict path writes O(new ops).
                "wire": r.get("wire"),
                "ckpt": r.get("ckpt"),
                "ckpt_delta_bytes_per_evict": r["server"].get(
                    "ckpt_delta_bytes_per_evict_mean", 0.0),
                "ckpt_full_bytes_per_evict": r["server"].get(
                    "ckpt_full_bytes_per_evict_mean", 0.0),
                # ISSUE 8: the obs registry/tracer block rides along so
                # the serve-lanes bench row records the same
                # observability fields as the serve row.
                "obs": r.get("obs"),
                # ISSUE 11: per-op provenance census (spans, audit
                # verdict, op-age percentiles) for the flow_* row
                # fields.
                "flow": r.get("flow"),
                # ISSUE 14: pipeline depth + prefill byte economy ride-
                # alongs (the lanes backend's by-order tables are
                # device-resident already, so its prefill block is the
                # no-surface default; the flat twin reports the cut).
                "pipeline": r.get("pipeline"),
                "prefill": r.get("prefill"),
            }
            for eng, r in reports.items()
        },
        "note": "CPU run: the lanes backend executes the real blocked "
                "kernel via the pallas interpreter (jitted to XLA "
                "CPU), so tick latencies are NOT silicon numbers; "
                "touched-rows/pass-traffic come from the kernel-exact "
                "step-cost replay of the lanes run's tick trace "
                "(shallow-YATA-scan model). Re-record on silicon via "
                "perf/when_up_r7.sh.",
    }
    return out


def sweep_k_workload(smoke: bool = False, ks=(8, 16, 32, 64)):
    """Serve-tuned K sweep (ROADMAP item 5 remainder): re-run the
    seeded serve loadgen on the lanes backend at several
    ``lanes_block_k`` values and replay each run's tick trace through
    the kernel-exact cost model.  The flat twin is skipped (its cost is
    K-independent); convergence per run leans on the loadgen's built-in
    always-resident oracle twin.  The chosen default minimizes blocked
    touched rows/step (NBT + K is the per-step floor, so the sweep is
    a real tradeoff: small K inflates the NBT logical table and the
    NB-way select chains, large K inflates every in-block pass), with
    pass traffic as the tiebreak."""
    rows = []
    for k in ks:
        t0 = time.perf_counter()
        out = serve_workload(smoke=smoke, block_k=k,
                             engines=("rle-lanes-mixed",))
        lanes = out["per_engine"]["rle-lanes-mixed"]
        assert lanes["converged"], f"K={k} loadgen diverged"
        rows.append({
            "lanes_block_k": k,
            "NB": out["workload"]["NB"],
            "NBT": out["workload"]["NBT"],
            "trace_steps": out["trace_steps"],
            "splits": out["splits"],
            "hint_misses": out["hint_misses"],
            "touched_rows_per_step":
                out["touched_rows_per_step"]["lanes_blocked"],
            "pass_traffic_per_step":
                out["pass_traffic_per_step"]["lanes_blocked"],
            "vs_flat_touched_ratio":
                out["touched_rows_per_step"]["ratio"],
            "tick_ms": lanes["tick_ms"],
            "wall_s": round(time.perf_counter() - t0, 1),
        })
        print(f"K={k}: touched/step "
              f"{rows[-1]['touched_rows_per_step']}, traffic/step "
              f"{rows[-1]['pass_traffic_per_step']}, splits "
              f"{rows[-1]['splits']} ({rows[-1]['wall_s']}s)",
              file=sys.stderr)
    best = min(rows, key=lambda r: (r["touched_rows_per_step"],
                                    r["pass_traffic_per_step"]))
    return {
        "workload": "serve loadgen tick trace (see serve_workload)",
        "smoke": smoke,
        "sweep": rows,
        "chosen_lanes_block_k": best["lanes_block_k"],
        "note": "CPU sim (kernel-exact step-cost replay; tick_ms is "
                "interpreter wall, not silicon). ServeConfig."
                "lanes_block_k carries the chosen default; re-validate "
                "on chip via perf/when_up_r8.sh.",
    }


def report(name, c: Counter, caps):
    tr = c.unb_touched / max(c.blk_touched, 1)
    pr = c.unb_traffic / max(c.blk_traffic, 1)
    print(f"{name}: caps {caps[0]}..{caps[-1]}, {c.steps} steps, "
          f"{c.splits} splits, hint misses "
          f"{c.hint_misses}/{max(c.hint_probes, 1)}")
    print(f"  touched rows/step: unblocked {c.unb_touched / c.steps:.0f}"
          f" vs blocked {c.blk_touched / c.steps:.0f}  -> "
          f"{tr:.1f}x fewer")
    print(f"  pass traffic/step: unblocked "
          f"{c.unb_traffic / c.steps:.0f} vs blocked "
          f"{c.blk_traffic / c.steps:.0f}  -> {pr:.1f}x less")
    return tr, pr


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=48,
                    help="lanes to simulate (iid workload; bench runs "
                         "2048 of the same distribution)")
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--block-k", type=int, default=32)
    ap.add_argument("--serve", action="store_true",
                    help="replay the serve loadgen tick trace instead "
                         "of configs 5/5r (ISSUE 4); writes "
                         "perf/serve_lanes_r7.json")
    ap.add_argument("--sweep-k", action="store_true",
                    help="with --serve: sweep the lanes backend's "
                         "lanes_block_k over --ks and record the "
                         "chosen default (writes perf/serve_k_sweep"
                         ".json unless --smoke)")
    ap.add_argument("--ks", default="8,16,32,64",
                    help="comma-separated K values for --sweep-k")
    ap.add_argument("--smoke", action="store_true",
                    help="with --serve: tiny workload (CI)")
    ap.add_argument("--out", default="perf/serve_lanes_r7.json")
    args = ap.parse_args()
    if args.serve and args.sweep_k:
        import jax

        jax.config.update("jax_platforms", "cpu")
        ks = tuple(int(x) for x in args.ks.split(","))
        out = sweep_k_workload(smoke=args.smoke, ks=ks)
        if not args.smoke:
            path = "perf/serve_k_sweep.json"
            with open(path, "w") as f:
                json.dump(out, f, indent=1)
            print(f"wrote {path}", file=sys.stderr)
        print(json.dumps(out))
        return 0
    if args.serve:
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = serve_workload(smoke=args.smoke)
        if not args.smoke:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
            print(f"wrote {args.out}", file=sys.stderr)
        print(json.dumps(out))
        ratio = out["touched_rows_per_step"]["ratio"]
        ok = out["bit_identical_flat_vs_lanes"] and ratio >= 5
        print(f"acceptance (bit-identical + >=5x touched-rows): "
              f"{'PASS' if ok else 'FAIL'} (ratio {ratio}x)",
              file=sys.stderr)
        return 0 if ok else 1
    c5, caps5 = config5_workload(args.docs, args.chunks, args.steps,
                                 args.block_k, remote=False)
    t5, _ = report("config 5  (local lanes)", c5, caps5)
    c5r, caps5r = config5_workload(args.docs, args.chunks, args.steps,
                                   args.block_k, remote=True)
    t5r, _ = report("config 5r (remote lanes)", c5r, caps5r)
    ok = t5 >= 10 and t5r >= 10
    print(f"acceptance (>=10x touched-rows on both): "
          f"{'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Round-4 northstar sweep: lanes x block_k x groups on the rle engine.

Run on the real chip AFTER `bench.py --config all` (one TPU process at a
time):

    python perf/sweep_r4.py [--quick]

Re-records the round-3 session table that was never captured in an
artifact (PERF.md §5 provenance caveat) and probes the §6.5 lever
(smaller planes x more groups).  Writes one JSON row per configuration
to perf/sweep_r4.json AS EACH COMPLETES (crash-safe, like bench.py's
RowSink), with oracle verification on every row.
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    flatten_patches,
    load_testing_data,
    trace_path,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="the 5 headline configs only (re-records + "
                         "the two measured-capacity geometries)")
    ap.add_argument("--out", default="perf/sweep_r4.json")
    args = ap.parse_args()

    data = load_testing_data(trace_path("automerge-paper"))
    patches = flatten_patches(data)
    merged = B.merge_patches(patches)
    lmax = max(len(p.ins_content) for p in merged)
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    n_ops = len(patches)
    want = data.end_content

    # (batch, block_k, groups, capacity). capacity=0 -> the shipped
    # 32768-row budget. 20992 = 164 blocks of 128: the MEASURED
    # physical requirement of this trace (interpret-mode kernel ground
    # truth: 162 blocks = 20,736 rows) plus TWO spare blocks — the
    # "smaller planes" lever: -36% plane VMEM admits 384-512 lanes.
    # Overflow is loud (capacity error flag), never silent.
    configs = [
        (128, 256, 1, 0),   # committed r3 row (637x) — re-record
        (256, 128, 1, 0),   # claimed 1026x geometry
        (384, 256, 1, 0),   # claimed 1035x geometry
        (384, 128, 1, 20992),  # measured-capacity, 1.5x lanes
        (512, 128, 1, 20992),  # measured-capacity, 2x lanes
    ]
    if not args.quick:
        configs += [
            (256, 256, 1, 0),
            (256, 64, 1, 0),
            (256, 128, 1, 20992),
            (128, 128, 2, 0),   # smaller planes x more groups (PERF §6.5)
            (128, 64, 4, 0),
            (256, 128, 4, 0),   # 1024 docs in one launch
            (256, 128, 40, 20992),  # 10,240 docs in ONE launch
        ]

    rows = []

    def flush():
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=1)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    for batch, block_k, groups, cap in configs:
        tag = f"b{batch}/k{block_k}/g{groups}/c{cap or 32768}"
        try:
            capacity = (((cap or 32768) + block_k - 1)
                        // block_k) * block_k
            stream = [ops] * groups if groups > 1 else ops
            run = R.make_replayer_rle(stream, capacity=capacity,
                                      batch=batch, block_k=block_k,
                                      chunk=1024)
            t0 = time.time()
            res = run()
            first = (res if groups == 1 else res[0])
            np.asarray(first.err)
            compile_s = time.time() - t0

            def batch_wall(n):
                t0 = time.time()
                for _ in range(n):
                    r_ = run()
                np.asarray((r_ if groups == 1 else r_[0]).err)
                return time.time() - t0, r_

            t1, _ = batch_wall(2)
            t2, r_ = batch_wall(6)
            wall = (t2 - t1) / 4
            got = SA.to_string(R.rle_to_flat(
                ops, r_ if groups == 1 else r_[0]))
            ok = got == want
            ops_s = n_ops * batch * groups / wall
            row = {"batch": batch, "block_k": block_k, "groups": groups,
                   "capacity": capacity,
                   "kernel_wall_s": round(wall, 4),
                   "ops_per_sec": round(ops_s, 1),
                   "compile_s": round(compile_s, 1),
                   "oracle_equal": bool(ok)}
            print(f"{tag}: {ops_s/1e9:.2f}G ops/s "
                  f"(wall {wall*1e3:.1f}ms, ok={ok})", flush=True)
        except Exception as e:
            row = {"batch": batch, "block_k": block_k, "groups": groups,
                   "capacity": capacity,
                   "error": f"{type(e).__name__}: {str(e)[:300]}"}
            print(f"{tag}: FAILED {type(e).__name__}", flush=True)
        rows.append(row)
        flush()
    print(f"wrote {len(rows)} rows to {args.out}", flush=True)


if __name__ == "__main__":
    main()

#!/bin/bash
# Second round-5 recovery watcher: the tunnel died again (~05:30 UTC)
# right after the fast-integrate kernel landed, so (a) the committed
# config-4 rows describe the PRE-fast kernel and (b) the new kernel has
# never compiled on real TPU.  On recovery: compile pins first (loud,
# bounded — if the new storm kernel is a Mosaic problem this is where
# it shows), then re-record config 4 only (all other rows are fresh at
# HEAD from this morning's re-record and their engines are untouched),
# then the storm scaling probe.  Safe to re-run.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r5b watcher)" >> perf/when_up_r5.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r5b)" >> perf/when_up_r5.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r5b.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r5b.log
python - <<'EOF'
import json, os
rows = json.load(open("BENCH_ALL.json"))
keep = [r for r in rows if r.get("cfg_key") != "4"]
if len(keep) != len(rows):
    with open("BENCH_ALL.json.tmp", "w") as f:
        json.dump(keep, f, indent=1)
    os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
timeout 7200 python bench.py --config all --resume >> perf/bench_all_r5.log 2>&1 \
  || echo "bench exited nonzero; rows up to the failure are persisted" \
       >> perf/bench_all_r5.log
exec timeout 3600 python perf/cfg4_probe.py >> perf/cfg4_probe_r5.log 2>&1

#!/bin/bash
# Round-7 recovery watcher (ISSUE 4 / ROADMAP #4): the serve, serve-lanes,
# and sp BENCH_ALL rows are CPU-recorded — the serving loop and the
# blocked-lanes serve backend have never run on silicon.  On tunnel
# recovery: compile-pin first (the serve shapes add small-B lane tiles
# (B=16) and chunk==bucket grids the 5/5r pins never exercised — if
# Mosaic rejects them, fail loudly here, not mid-bench), then a lanes
# loadgen smoke ON DEVICE (interpret off via backend auto-detect), then
# drop and re-record ONLY the three CPU rows plus the northstar sanity
# row via the resume path.
# Safe to re-run; appends to perf/when_up_r7.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r7 watcher)" >> perf/when_up_r7.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r7)" >> perf/when_up_r7.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r7.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r7.log
# On-device serve smoke on the blocked lanes backend (tiny; proves the
# serve tick path compiles on real Mosaic before the full re-record).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --docs 8 \
  --ticks 6 --events-per-tick 8 --engine rle-lanes-mixed --device \
  >> perf/when_up_r7.log 2>&1 \
  || echo "serve-lanes device smoke FAILED rc=$?" >> perf/when_up_r7.log
# Drop the superseded CPU rows, then re-record them + northstar.
python - <<'EOF'
import json, os
rows = json.load(open("BENCH_ALL.json"))
keep = [r for r in rows
        if r.get("cfg_key") not in ("serve", "serve-lanes", "sp")]
if len(keep) != len(rows):
    with open("BENCH_ALL.json.tmp", "w") as f:
        json.dump(keep, f, indent=1)
    os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
timeout 10800 python bench.py --config all --resume \
  >> perf/bench_all_r7.log 2>&1 \
  || echo "bench exited nonzero; rows up to the failure are persisted" \
       >> perf/bench_all_r7.log
echo "$(date -u +%H:%M:%S) r7 re-record done" >> perf/when_up_r7.log

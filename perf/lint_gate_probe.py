"""Measure the tcrlint v2 gate cost model (ISSUE 15, PERF.md §20).

Three walls + the loudness matrix, written to a committed JSON:

- **full-cold**: whole-package lint, cache emptied first — the
  worst-case weekly-style run;
- **full-warm**: same walk again — every per-file verdict served from
  the content-hash cache (the steady-state cost of the full fallback);
- **changed**: ``--changed`` against the merge-base — the tier-1
  gate's shipped mode (on a committed clean tree this lints 0 files
  and prices only the project-level passes);
- **injection matrix**: one seeded defect per check family through
  ``run_lint``, recording that the family fires with its exact id —
  the committed proof the claims tests re-check without re-measuring.

Usage: ``python perf/lint_gate_probe.py [--out perf/lint_gate_r17.json]``
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE = os.path.join(REPO, ".tcrlint_cache")

#: One minimal seeded defect per family -> the check id it must raise.
INJECTIONS = {
    "TCR-W001": "import time\n\n\ndef f():\n    return time.time()\n",
    "TCR-D001": "def f(x):\n    return hash(x)\n",
    "TCR-D002": "def f(xs):\n    return list(set(xs))\n",
    "TCR-D003": "import os\n\n\ndef f(d):\n    return os.listdir(d)\n",
    "TCR-D004": "import random\n\n\ndef f():\n    return random.random()\n",
    "TCR-F401": "import json\n\nX = 1\n",
    "TCR-P001": textwrap.dedent("""\
        def tick(backend, stacked):
            backend.apply(stacked)
            stacked.pos[0] = 7
        """),
    "TCR-M002": textwrap.dedent("""\
        class NewBackend:
            def seed(self, b):
                self.state = self.state.at[b].set(0)
        """),
    "TCR-K001": textwrap.dedent("""\
        def stage(stream, pad_ops):
            return pad_ops(stream, 48)
        """),
}


def lint_cli(*args):
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--json", *args],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    wall = time.perf_counter() - t0
    out = json.loads(r.stdout)
    return {"wall_s": round(wall, 3), "rc": r.returncode,
            "files": out["stats"]["files"],
            "findings": len(out["findings"]),
            "cache": out["stats"].get("cache"),
            "mode": out["stats"].get("mode")}


def injection_matrix():
    from text_crdt_rust_tpu.analysis import run_lint
    from text_crdt_rust_tpu.analysis.checks_shape import SHAPE_PINS_PATH

    matrix = {}
    for check, src in sorted(INJECTIONS.items()):
        with tempfile.TemporaryDirectory() as td:
            rel = ("text_crdt_rust_tpu/serve/mod.py"
                   if check == "TCR-M002" else "mod.py")
            full = os.path.join(td, rel)
            os.makedirs(os.path.dirname(full), exist_ok=True)
            with open(full, "w") as f:
                f.write(src)
            findings, _ = run_lint(
                td, allowlist_path=os.path.join(td, "a.json"),
                pins_path=os.path.join(td, "p.json"),
                shape_pins_path=(SHAPE_PINS_PATH
                                 if check == "TCR-K001"
                                 else os.path.join(td, "sp.json")))
            hits = [f.format() for f in findings if f.check == check]
            matrix[check] = {"loud": bool(hits),
                             "finding": hits[0] if hits else None}
    # TCR-M001 and the C-family need richer trees; they are proven by
    # tests/test_analysis_dataflow.py — recorded here by reference.
    for check in ("TCR-M001", "TCR-C001", "TCR-C002", "TCR-C003"):
        matrix[check] = {"loud": True,
                         "finding": "tests/test_analysis_dataflow.py"}
    return matrix


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "perf", "lint_gate_r17.json"))
    a = ap.parse_args(argv)
    if os.path.isdir(CACHE):
        shutil.rmtree(CACHE)
    full_cold = lint_cli()
    full_warm = lint_cli()
    changed = lint_cli("--changed")
    matrix = injection_matrix()
    report = {
        "probe": "lint_gate_probe",
        "round": 17,
        "full_cold": full_cold,
        "full_warm": full_warm,
        "changed": changed,
        "cache_hit_rate_warm": (
            round(full_warm["cache"]["hits"]
                  / max(1, full_warm["cache"]["hits"]
                        + full_warm["cache"]["misses"]), 3)
            if full_warm["cache"] else None),
        "injection_matrix": matrix,
        "all_families_loud": all(v["loud"] for v in matrix.values()),
        "gate_budget_s": 15,
        "inside_budget": (full_cold["wall_s"] < 15
                          and changed["wall_s"] < 15),
    }
    with open(a.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if (report["all_families_loud"]
                 and report["inside_budget"]
                 and full_cold["rc"] == 0) else 1


if __name__ == "__main__":
    raise SystemExit(main())

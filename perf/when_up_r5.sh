#!/bin/bash
# Round-5 tunnel-recovery watcher. EVERY kernel benched in round 4
# changed after the 05:23 records (VERDICT r4 weak #1), so round 5
# re-records the WHOLE table fresh at HEAD: back up the stale table,
# move it aside, run the full suite (with --resume so a crash-restart
# keeps finished rows), then the geometry sweep. Safe to re-run.
set -eu
cd /root/repo
if [ -f BENCH_ALL.json ] && [ ! -e perf/BENCH_ALL_r4_stale.json ]; then
  # The r4 rows describe pre-outage kernels; archive, don't resume them.
  cp BENCH_ALL.json perf/BENCH_ALL_r4_stale.json
  python - <<'EOF'
import json, os
rows = json.load(open("BENCH_ALL.json"))
for r in rows:
    r["stale"] = "r4-pre-outage kernels; superseded by r5 re-record"
with open("BENCH_ALL.json.tmp", "w") as f:
    json.dump(rows, f, indent=1)
os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
fi
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back" >> perf/when_up_r5.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down" >> perf/when_up_r5.log
  sleep 120
done
# Fresh table: drop the stale-stamped r4 rows BEFORE --resume sees
# them. This step is load-bearing: their variant string matches
# HEAD's defaults, so without the drop RowSink would count them as
# clean same-variant rows, mark those configs done, and skip the
# re-record — exactly the stale-table failure this script exists
# to prevent. (A crash-restart mid-suite is still safe: fresh rows
# carry no "stale" key and are kept.)
python - <<'EOF'
import json, os
if os.path.exists("BENCH_ALL.json"):
    rows = [r for r in json.load(open("BENCH_ALL.json"))
            if not r.get("stale")]
    with open("BENCH_ALL.json.tmp", "w") as f:
        json.dump(rows, f, indent=1)
    os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
# A mid-suite crash (e.g. a kevin OOM) must not eat the pins/sweep:
# finished rows are already persisted per-config by RowSink, and the
# log carries the failure loudly.
python bench.py --config all --resume >> perf/bench_all_r5.log 2>&1 || \
  echo "bench exited nonzero; rows up to the failure are persisted" \
    >> perf/bench_all_r5.log
# One TPU process at a time: geometry compile pins (fail loudly on a
# shape regression, VERDICT r4 next #6), then the measured-capacity
# sweep. `|| true` on the pin: a pin failure must not eat the sweep —
# its log is the loud signal.
python perf/compile_pin.py >> perf/compile_pin_r5.log 2>&1 || true
exec python perf/sweep_r4.py --quick >> perf/sweep_r5_run.log 2>&1

"""Config-4 storm cost model: where do the 43us/step go?

Replays the 16-peer concurrent-insert storm on the rle_mixed engine at
several ROUND counts and lane widths.  If wall grows ~quadratically in
rounds, the YATA scan's run-walk dominates (iterations ~ peers x
rounds per op); if ~linearly, the fixed per-step cost does.

    python perf/cfg4_probe.py
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_mixed as RM
from text_crdt_rust_tpu.utils.randedit import make_storm


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    for rounds in (50, 100, 200):
        txns, receiver = make_storm(16, rounds, 4, seed=7)
        table = B.AgentTable(sorted({t.id.agent for t in txns}))
        ops, _ = B.compile_remote_txns(txns, table, lmax=8, dmax=16)
        n_chars = 16 * rounds * 4
        block_k = 128
        capacity = ((max(int(ops.num_steps * 3), 256) + block_k - 1)
                    // block_k) * block_k
        for batch in (128,) if rounds != 200 else (128, 256):
            run = RM.make_replayer_rle_mixed(
                ops, capacity=capacity, batch=batch, block_k=block_k,
                chunk=1024)
            res = run()
            np.asarray(res.err)  # compile + warm
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                res = run()
            np.asarray(res.err)
            dt = (time.perf_counter() - t0) / reps
            print(f"rounds={rounds} steps={ops.num_steps} b={batch} "
                  f"cap={capacity}: {dt*1e3:.1f}ms "
                  f"({dt/ops.num_steps*1e6:.1f}us/step, "
                  f"{n_chars/dt:,.0f} chars/s)", flush=True)


if __name__ == "__main__":
    main()

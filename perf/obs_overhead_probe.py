"""Trace-overhead probe (ISSUE 8 acceptance): tracing-on vs tracing-off
loadgen wall delta at the 200-doc acceptance shape.

The obs/ tracer is DEFAULT-ON in ``ServeConfig`` — the flight recorder
is only useful if it was running when the failure happened — so its
cost must be pinned, not assumed.  The probe runs the same seeded
loadgen three ways:

- ``off``  — ``ServeConfig(trace=False)``: the tracer no-ops, the
  registry still counts (counters were always on);
- ``on``   — the default: tracer + ring + recorder + histograms;
- ``on2``  — a second traced run, whose logical trace must be
  BYTE-IDENTICAL to ``on``'s (the determinism guard at full scale,
  not just the tier-1 small shape).

Each timing arm takes the MIN of ``reps`` runs (wall noise on a shared
box swamps a percent-level delta; min-of-N is the standard defense —
the same argument as bench.py's baseline sampling), and the loop wall
(``device_ticks_wall_s``, the serving loop only) is the comparison
basis — verification/drain phases are not serving cost.

Acceptance: overhead < 5% (``floor``), both runs converged, traces
byte-identical.  Writes ``perf/obs_overhead_r11.json``.

Run: python perf/obs_overhead_probe.py [--smoke] [--reps N] [--out PATH]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

FLOOR_PCT = 5.0


def run_one(trace: bool, smoke: bool, seed: int = 7,
            keep_trace: bool = False):
    """One seeded loadgen run; returns (report, logical_trace_bytes)."""
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=16,
                      trace=trace, trace_keep=keep_trace)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                       events_per_tick=events, zipf_alpha=1.1,
                       fault_rate=0.10, local_prob=0.25, seed=seed,
                       cfg=cfg)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    trace_bytes = (gen.server.tracer.logical_bytes()
                   if keep_trace else None)
    return rep, trace_bytes


def run_matrix(smoke: bool = False, reps: int = 2) -> dict:
    arms = {}
    timings = {"off": [], "on": []}
    for arm in ("off", "on"):
        for _r in range(reps):
            # Timed arms NEVER set trace_keep: retaining the full event
            # list in memory is a test-harness cost the shipped default
            # (ring only) doesn't pay, and it must not contaminate the
            # <5% acceptance number.
            t0 = time.perf_counter()
            rep, _ = run_one(arm == "on", smoke)
            wall = time.perf_counter() - t0
            timings[arm].append({
                "total_wall_s": round(wall, 3),
                "loop_wall_s": rep["device_ticks_wall_s"],
            })
            arms[arm] = rep
    # Determinism at the probe shape, measured on two UNTIMED traced
    # runs: byte-identical logical streams.
    _repa, trace_a = run_one(True, smoke, keep_trace=True)
    _repb, trace_b = run_one(True, smoke, keep_trace=True)
    trace_identical = trace_a == trace_b

    loop_off = min(t["loop_wall_s"] for t in timings["off"])
    loop_on = min(t["loop_wall_s"] for t in timings["on"])
    total_off = min(t["total_wall_s"] for t in timings["off"])
    total_on = min(t["total_wall_s"] for t in timings["on"])
    overhead_pct = round((loop_on - loop_off) / loop_off * 100.0, 2)
    out = {
        "probe": "obs_overhead",
        "smoke": smoke,
        "workload": {
            "docs": arms["on"]["docs"], "seed": 7, "engine": "flat",
            "fault_rate": 0.10, "reps_per_arm": reps,
            "basis": "min loop wall (device_ticks_wall_s) per arm",
        },
        "loop_wall_s": {"off": round(loop_off, 3), "on": round(loop_on, 3)},
        "total_wall_s": {"off": round(total_off, 3),
                         "on": round(total_on, 3)},
        "overhead_pct": overhead_pct,
        "total_overhead_pct": round(
            (total_on - total_off) / total_off * 100.0, 2),
        "trace_events": arms["on"]["obs"]["trace_events"],
        "trace_bytes_logical": len(trace_a) if trace_a else 0,
        "trace_byte_identical_across_runs": trace_identical,
        "converged": {k: arms[k]["converged"] for k in arms},
        "acceptance": {
            "floor_pct": FLOOR_PCT,
            "pass": bool(overhead_pct < FLOOR_PCT and trace_identical
                         and all(a["converged"] for a in arms.values())),
        },
        "note": "CPU run (tier-1 harness); the tracer cost is host-side "
                "python (event dicts + ring append) and does not change "
                "with the device backend, so the CPU bound transfers. "
                "Negative overhead = run-to-run noise floor exceeds the "
                "tracer cost.",
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default="perf/obs_overhead_r11.json")
    a = ap.parse_args()
    out = run_matrix(smoke=a.smoke, reps=a.reps)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    if not out["acceptance"]["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

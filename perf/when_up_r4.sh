#!/bin/bash
# Round-4 tunnel-recovery watcher: wait for the TPU to come back, then
# (1) drop the northstar + config-4 rows so they re-record on the
# incremental-descent / incremental-prefix kernels, (2) run the suite
# with --resume (configs 1-3,5 keep their clean rows; northstar,
# config 4 and kevin's error row run fresh). Safe to re-run: the backup is
# taken once (cp -n) and any failure before the bench aborts the script
# instead of silently resuming past a stale row.
set -eu
cd /root/repo
# Back up once; a REAL copy failure must abort (set -e), while
# "already backed up" / "nothing to back up" skip explicitly.
[ ! -f BENCH_ALL.json ] || [ -e perf/BENCH_ALL_pre_kevin.json ] || \
  cp BENCH_ALL.json perf/BENCH_ALL_pre_kevin.json
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back" >> perf/when_up_r4.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down" >> perf/when_up_r4.log
  sleep 180
done
python - <<'EOF'
import json, os
rows = json.load(open("BENCH_ALL.json"))
# Re-record the rows whose kernels changed this round: northstar (rle
# incremental descent) and config 4 (rle-mixed incremental prefixes).
rows = [r for r in rows if r.get("cfg_key") not in ("northstar", "4")]
with open("BENCH_ALL.json.tmp", "w") as f:
    json.dump(rows, f, indent=1)
os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
python bench.py --config all --resume >> perf/bench_all_r4c.log 2>&1
# One TPU process at a time: the sweep (measured-capacity geometries,
# 10k-doc single launch) runs only after the suite finishes.
exec python perf/sweep_r4.py --quick >> perf/sweep_r4_run.log 2>&1

"""Differential fuzz: vectorized YATA scan vs serial walk vs oracle.

Random N-peer concurrent-edit streams (inserts, deletes, periodic
cross-merges — windows full of siblings, descendants, split pieces,
merge-appended runs, mid-run cursors) replayed through the mixed RLE
engine with ``fast_integrate`` ON and OFF: final device state must be
BIT-IDENTICAL and match the oracle string.  CPU interpret mode.

    python perf/fuzz_mixed_fast.py [n_seeds] [seed0] [hard]

``hard`` widens the stream shape: 3-6 peers, 5-9 rounds — deeper
histories, more concurrent sibling groups and split churn per window.
"""
import random
import sys
import time

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from text_crdt_rust_tpu.models.oracle import ListCRDT  # noqa: E402
from text_crdt_rust_tpu.models.sync import export_txns_since  # noqa: E402
from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import rle as R  # noqa: E402
from text_crdt_rust_tpu.ops import rle_mixed as RM  # noqa: E402
from text_crdt_rust_tpu.ops import span_arrays as SA  # noqa: E402


def gen_stream(seed, hard=False):
    """Random multi-peer txn stream with cross-merges (causally valid,
    round-robin interleaved)."""
    rng = random.Random(seed)
    n_peers = rng.randint(3, 6) if hard else rng.randint(2, 4)
    names = rng.sample(
        ["amy", "bob", "cyd", "dee", "eve", "fay", "gus", "hal"], n_peers)
    docs, agents, marks = [], [], []
    for nm in names:
        d = ListCRDT()
        agents.append(d.get_or_create_agent_id(nm))
        docs.append(d)
        marks.append(0)
    applied = [set() for _ in range(n_peers)]
    flat = []
    for _ in range(rng.randint(5, 9) if hard else rng.randint(3, 7)):
        for i in range(n_peers):
            d, g = docs[i], agents[i]
            for _ in range(rng.randint(1, 4)):
                n = len(d)
                if n == 0 or rng.random() < 0.55:
                    pos = rng.randint(0, n)
                    d.local_insert(g, pos, "".join(
                        rng.choice("abcdefgh")
                        for _ in range(rng.randint(1, 4))))
                else:
                    pos = rng.randint(0, n - 1)
                    d.local_delete(g, pos,
                                   min(rng.randint(1, 4), n - pos))
            flat.extend(export_txns_since(d, marks[i]))
        # Each peer independently merges a random prefix of history
        # (sometimes everything, sometimes lagging — divergent views).
        for i in range(n_peers):
            if rng.random() < 0.8:
                upto = rng.randint(0, len(flat))
                for t in flat[:upto]:
                    key = (t.id.agent, t.id.seq)
                    if t.id.agent != names[i] and key not in applied[i]:
                        applied[i].add(key)
                        docs[i].apply_remote_txn(t)
            marks[i] = docs[i].get_next_order()
    return flat


def run_one(seed, hard=False):
    txns = gen_stream(seed, hard)
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    ops, _ = B.compile_remote_txns(txns, table, lmax=4, dmax=None)
    # Bucket the device shapes (steps to the next power-of-two 128
    # multiple, capacity likewise): seeds then share a handful of
    # traced kernels instead of re-tracing per seed — with the oracle's
    # order->index map this is what took the driver from ~34s/seed to
    # seconds (PERF.md §9).
    s_bkt = 128
    while s_bkt < ops.num_steps:
        s_bkt *= 2
    ops = B.pad_ops(ops, s_bkt)
    cap = max(256, ((3 * ops.num_steps + 127) // 128) * 128)
    cap = 1 << max(cap - 1, 1).bit_length()
    outs = []
    for fast in (True, False):
        res = RM.replay_mixed_rle(ops, capacity=cap, batch=8, block_k=8,
                                  chunk=128, interpret=True,
                                  fast_integrate=fast)
        res.check()
        outs.append(R.rle_to_flat(ops, res))
    oracle = ListCRDT()
    for t in txns:
        oracle.apply_remote_txn(t)
    want = oracle.to_string()
    fast_s, serial_s = SA.to_string(outs[0]), SA.to_string(outs[1])
    assert serial_s == want, f"seed {seed}: serial != oracle"
    assert fast_s == want, f"seed {seed}: fast != oracle"
    assert np.array_equal(np.asarray(outs[0].signed),
                          np.asarray(outs[1].signed)), \
        f"seed {seed}: fast/serial state mismatch"
    return len(txns)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    s0 = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    hard = len(sys.argv) > 3 and sys.argv[3] == "hard"
    t0 = time.time()
    total = 0
    for i in range(n):
        total += run_one(s0 + i, hard)
        if (i + 1) % 10 == 0:
            print(f"{i + 1}/{n} seeds ok ({total} txns, "
                  f"{time.time() - t0:.0f}s)", flush=True)
    print(f"PASS: {n} seeds (base {s0}{', hard' if hard else ''}), "
          f"{total} txns, zero divergences, {time.time() - t0:.0f}s",
          flush=True)


if __name__ == "__main__":
    main()

"""Long-running differential fuzz: the sharded SpDoc (full op surface)
vs the oracle on the 8-device virtual CPU mesh.

Each round: a random mix of local patches and two-peer remote history
applied through ``parallel.sp_apply.SpDoc`` (chunked, auto_reshard) —
signed per-char equality with the oracle after every chunk.  One SpDoc
and one compiled replay are reused across rounds (state is re-zeroed
host-side), so rounds after the first are cheap.

    python perf/fuzz_sp_remote.py [--rounds N] [--start-seed S]
"""
import argparse
import os
import random
import sys
import time

sys.path.insert(0, ".")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.parallel import make_mesh
from text_crdt_rust_tpu.parallel.sp_apply import TAB_UNKNOWN, SpDoc
from text_crdt_rust_tpu.utils.randedit import random_patches


def reset(doc: SpDoc) -> None:
    sharding = NamedSharding(doc.mesh, P("sp"))
    z = lambda n: jax.device_put(jnp.zeros(n, jnp.int32), sharding)
    doc.ordp = z(doc.nsp * doc.R)
    doc.lenp = z(doc.nsp * doc.R)
    doc.rows = jax.device_put(jnp.zeros(doc.nsp, jnp.int32), sharding)
    doc.oll = jax.device_put(
        jnp.full(doc.nsp * doc.OTS, TAB_UNKNOWN, jnp.int32), sharding)
    doc.orl = jax.device_put(
        jnp.full(doc.nsp * doc.OTS, TAB_UNKNOWN, jnp.int32), sharding)
    doc.rkl = z(doc.nsp * doc.OTS)
    doc.ol_log.clear()
    doc.or_log.clear()


def peer(rng, n, agent):
    d = ListCRDT()
    a = d.get_or_create_agent_id(agent)
    patches, _ = random_patches(rng, n)
    for p in patches:
        if p.del_len:
            d.local_delete(a, p.pos, p.del_len)
        if p.ins_content:
            d.local_insert(a, p.pos, p.ins_content)
    return d


def one_round(doc: SpDoc, seed: int, lanes_diff: bool = True) -> int:
    rng = random.Random(seed)
    reset(doc)
    oracle = ListCRDT()
    txns = (export_txns_since(peer(rng, 10 + rng.randrange(20), "pa"), 0)
            + export_txns_since(peer(rng, 10 + rng.randrange(20), "pb"),
                                0))
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    assigner = None
    step = max(3, len(txns) // (1 + rng.randrange(4)))
    for at in range(0, len(txns), step):
        chunk = txns[at:at + step]
        for t in chunk:
            oracle.apply_remote_txn(t)
        ops, assigner = B.compile_remote_txns(
            chunk, table, assigner=assigner, lmax=6, dmax=None)
        doc.apply_stream(ops)
        want = [(-1 if oracle.deleted[i] else 1)
                * (int(oracle.order[i]) + 1) for i in range(oracle.n)]
        got = doc.expand().tolist()
        assert got == want, f"seed {seed} chunk@{at} DIVERGED"
    if lanes_diff:
        # ISSUE-2 ride-along: the same stream through the BLOCKED and
        # un-blocked per-lane mixed engines must match the oracle (and
        # therefore the sharded SpDoc) bit-identically.
        from text_crdt_rust_tpu.ops import rle_lanes as RL
        from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM

        ops_all, _ = B.compile_remote_txns(txns, table, lmax=6,
                                           dmax=None)
        stacked = B.stack_ops([ops_all])
        want = [(-1 if oracle.deleted[i] else 1)
                * (int(oracle.order[i]) + 1) for i in range(oracle.n)]
        for name, res in (
            ("flat", RLM.replay_lanes_mixed(
                stacked, capacity=512, chunk=32, interpret=True)),
            ("blocked", RLM.replay_lanes_mixed_blocked(
                stacked, capacity=512, block_k=32, chunk=32,
                interpret=True)),
        ):
            res.check()
            assert RL.expand_lane(res, 0).tolist() == want, \
                f"seed {seed} lanes-{name} DIVERGED"
    return oracle.n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--start-seed", type=int, default=40_000)
    args = ap.parse_args()
    mesh = make_mesh(sp=8)
    doc = SpDoc(mesh, shard_rows=96, order_rows=64, auto_reshard=True)
    t0 = time.time()
    total = 0
    for k in range(args.rounds):
        total += one_round(doc, args.start_seed + k)
        if (k + 1) % 5 == 0:
            print(f"{k + 1}/{args.rounds} rounds, {total} chars, "
                  f"{time.time() - t0:.0f}s", flush=True)
    print(f"sp fuzz OK: {args.rounds} rounds, {total} chars, "
          f"{time.time() - t0:.0f}s", flush=True)


if __name__ == "__main__":
    main()

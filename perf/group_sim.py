"""Sizes VERDICT r3 next #7 (multi-op steps: W ops from W disjoint blocks).

Simulates the rle engine's block layout (block_k=128, kernel split rule)
over the merged op streams and greedily packs consecutive ops into
steps when pairwise slot distance >= 2, no split is pending, and the op
touches one block.  Result (2026-07-30):

    automerge-paper: 10,712 ops -> 10,243 steps = 1.05 ops/step
                     (sizes {1: 9815, 2: 391, 3: 33, 4: 4})
    rustcode:        12,219 ops -> 11,468 steps = 1.07 ops/step

The hypothesized ~3-4x at W=4 does not exist for consecutive-op
grouping: real typing traces are position-LOCAL, so consecutive merged
ops almost always hit the same or an adjacent block.  A useful multi-op
step would need out-of-order scheduling across a lookahead window,
which changes apply semantics (origins read pre-step state) — rejected.
Run: python perf/group_sim.py
"""
import sys; sys.path.insert(0, ".")
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.utils.testdata import flatten_patches, load_testing_data, trace_path

# Simulate the rle engine's block layout (block_k K, splits at r+2>K keep=r//2)
# and measure: for consecutive merged ops, how often can W=2,4 ops be grouped
# into one step (pairwise slot distance >= 2, no split needed, single-block op)?
def simulate_groups(patches, K=128, W=4):
    # runs per logical slot: list of lists of (order, len, live)
    slots = [[]]
    def live_of(slot): return sum(l for o,l,v in slot if v)
    def rows_of(slot): return len(slot)
    next_order = 0
    # op -> (slot_idx, needs_split, multi_block)
    infos = []
    for p in patches:
        # find slot by live rank
        def slot_of_rank(p_rank):
            acc = 0
            for i, s in enumerate(slots):
                lv = live_of(s)
                if acc + lv >= p_rank and (p_rank > acc or i == 0):
                    return i
                acc += lv
            return len(slots) - 1
        multi = False
        split = False
        touched = set()
        if p.del_len:
            # walk blocks like the kernel
            rem = p.del_len
            guard = 0
            while rem > 0 and guard < 10000:
                guard += 1
                li = slot_of_rank(p.pos + 1)
                s = slots[li]
                if rows_of(s) + 2 > K:
                    split = True
                    # perform split
                    keep = len(s)//2
                    slots[li:li+1] = [s[:keep], s[keep:]]
                    continue
                touched.add(li)
                # apply delete within this block
                before = sum(live_of(slots[j]) for j in range(li))
                out = []
                covered = 0
                pos_in = before
                for (o, l, v) in s:
                    lv = l if v else 0
                    cs = min(max(p.pos - pos_in, 0), lv)
                    ce = min(max(p.pos + rem - pos_in, 0), lv)
                    cov = ce - cs
                    if cov > 0 and v:
                        if cs > 0: out.append((o, cs, True))
                        out.append((o + cs, cov, False))
                        if ce < l: out.append((o + ce, l - ce, True))
                        covered += cov
                    else:
                        out.append((o, l, v))
                    pos_in += lv - cov
                slots[li] = out
                rem -= covered
                if covered == 0: break
            next_order += p.del_len
            multi = len(touched) > 1
        il = len(p.ins_content)
        if il:
            li = slot_of_rank(p.pos) if p.pos else 0
            s = slots[li]
            if rows_of(s) + 2 > K:
                split = True
                keep = len(s)//2
                slots[li:li+1] = [s[:keep], s[keep:]]
                li = slot_of_rank(p.pos) if p.pos else 0
                s = slots[li]
            touched.add(li)
            # apply insert (simplified: append new run at right place)
            st = next_order
            before = sum(live_of(slots[j]) for j in range(li))
            local = p.pos - before
            acc = 0
            done = False
            for i2, (o, l, v) in enumerate(s):
                lv = l if v else 0
                if acc + lv >= local and local > 0:
                    off = local - acc
                    if off == l and v and st == o + l:
                        s[i2] = (o, l + il, True)
                    elif off == lv:
                        s.insert(i2 + 1, (st, il, True))
                    else:
                        s[i2:i2+1] = [(o, off, True), (st, il, True), (o + off, l - off, True)]
                    done = True
                    break
                acc += lv
            if not done:
                s.insert(0, (st, il, True))
            next_order += il
        infos.append((min(touched) if touched else 0, split, multi))
    # grouping: greedy consecutive packing
    groups = 0
    i = 0
    sizes = []
    n = len(infos)
    while i < n:
        cnt = 1
        used = {infos[i][0]}
        if not infos[i][1] and not infos[i][2]:
            j = i + 1
            while j < n and cnt < W:
                sl, sp, mu = infos[j]
                if sp or mu or any(abs(sl - u) < 2 for u in used):
                    break
                used.add(sl); cnt += 1; j += 1
        sizes.append(cnt)
        groups += 1
        i += cnt
    import collections
    hist = collections.Counter(sizes)
    total = len(infos)
    print(f"  ops {total} -> steps {groups} ({total/groups:.2f} ops/step); group sizes {dict(sorted(hist.items()))}")

for trace in ("automerge-paper", "rustcode"):
    patches = B.merge_patches(flatten_patches(load_testing_data(trace_path(trace))))
    print(trace)
    simulate_groups(patches)

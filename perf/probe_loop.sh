#!/bin/bash
cd /root/repo
for i in $(seq 1 40); do
  timeout 90 python -c "
import jax, jax.numpy as jnp
y = (jnp.ones((64,64))@jnp.ones((64,64))).sum()
print('CHIP_OK', float(y))" 2>/dev/null | grep CHIP_OK && exit 0
  sleep 60
done
exit 1

"""Device-resident prefill probe (ISSUE 14 acceptance): the delta-
scatter serve tick vs the full-log host round trip, at the 200-doc
faulted acceptance shape.

Four arms of the SAME seeded loadgen (the ``pipeline_probe`` pattern):
{host-prefill, delta-prefill} x pipeline depth {1, 2}.  Every arm's
logical stream is sha256-hashed and ALL FOUR must be identical — the
prefill mode and the pipeline depth may move bytes and wall only.  Per
arm the probe records:

- **prefill bytes moved per tick**: the delta path ships the padded
  scatter tensors (7 u32 columns x bucket length x lanes); the host
  path materializes AND re-uploads the four full [B, OCAP] logs
  (2 x 4 x OCAP x B x 4 bytes).  The committed cut must be >= 20x
  (the acceptance floor; the §19 cost model predicts ~40x at this
  shape).
- **loop wall** (min of ``reps``): the delta arm must not regress the
  host arm > 5% at either depth.  On the CPU tier-1 box the prefill
  round trip is a small slice of the tick, so the honest readout is
  parity-within-noise; the silicon re-record (perf/when_up_r14.sh) is
  where the removed dispatch-edge sync actually pays.
- **scatter economy**: un-padded scatter length, compiled
  scatter-bucket count (steady state must stay bounded), and the
  flow/ledger counters that must not move across arms.

Writes ``perf/device_prefill_r16.json``.

Run: python perf/device_prefill_probe.py [--smoke] [--reps N] [--out P]
"""
import argparse
import hashlib
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

WALL_REGRESSION_PCT = 5.0
BYTES_CUT_FLOOR_X = 20.0
ARMS = tuple((dp, pt) for dp in ("delta", "host") for pt in (2, 1))


def run_one(smoke: bool, *, device_prefill: bool, pipeline_ticks: int,
            seed: int = 7):
    """One seeded loadgen run; returns (report, loop_wall_s, sha256)."""
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=16,
                      device_prefill=device_prefill,
                      pipeline_ticks=pipeline_ticks,
                      flow_sample_mod=16, trace_keep=True)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                       events_per_tick=events, zipf_alpha=1.1,
                       fault_rate=0.10, local_prob=0.25, seed=seed,
                       cfg=cfg)
    t0 = time.perf_counter()
    rep = gen.run()
    wall = time.perf_counter() - t0
    assert rep["converged"], rep["mismatches"][:4]
    sha = hashlib.sha256(
        gen.server.tracer.logical_bytes()).hexdigest()
    return rep, wall, sha


def _arm_row(rep: dict) -> dict:
    pf = rep["prefill"]
    return {
        "device_prefill": pf["device_prefill"],
        "pipeline_ticks": rep["pipeline"]["ticks"],
        "overlap_frac": rep["pipeline"]["overlap_frac"],
        "loop_wall_s": rep["device_ticks_wall_s"],
        "prefill_bytes_per_tick": pf["bytes_per_tick"],
        "prefill_bytes_full_per_tick": pf["bytes_full_per_tick"],
        "prefill_bytes_cut_x": pf["bytes_cut_x"],
        "prefill_scatter_len": pf["scatter_len"],
        "prefill_scatter_compiles": pf["scatter_compiles"],
        "device_steps": rep["server"].get("device_steps", 0),
        "device_compiles": rep["server"].get("device_compiles", 0),
        "evictions": rep["server"].get("evictions", 0),
        "flow_audit_ok": rep["flow"]["audit_ok"],
        "flow_age_p50": rep["flow"]["ages_ticks"]["p50"],
    }


def _warm_compiles(smoke: bool) -> None:
    """Warm every jit cache untimed BEFORE any timed arm: the step
    programs via one smoke run per mode, and the scatter programs for
    EVERY bucket a full-scale tick can hit (the smoke run's small
    scatters never reach the big buckets, and a mid-arm ~0.7 s scatter
    compile would bill compiler order as prefill cost — the first cut
    of this probe measured exactly that)."""
    import numpy as np

    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.ops import flat as F
    from text_crdt_rust_tpu.serve.batcher import FlatLaneBackend

    for dp in (True, False):
        run_one(True, device_prefill=dp, pipeline_ticks=2)
    cfg = ServeConfig()
    backend = FlatLaneBackend(lanes=cfg.lanes_per_shard,
                              capacity=cfg.lane_capacity,
                              order_capacity=cfg.order_capacity,
                              lmax=cfg.lmax)
    bucket_cap = cfg.step_buckets[-1] * cfg.lmax
    L = B.PREFILL_BUCKET_BASE
    while L <= bucket_cap:
        pad = np.full((cfg.lanes_per_shard, L), B.PREFILL_PAD,
                      np.uint32)
        zero = np.zeros_like(pad)
        delta = B.PrefillDelta(pad, zero, zero, pad, zero, pad, zero,
                               bucket=L)
        F.apply_prefill_delta(backend.docs, delta)
        L *= 4


def run_matrix(smoke: bool = False, reps: int = 2) -> dict:
    _warm_compiles(smoke)
    arms = {}
    hashes = {}
    walls = {f"{dp}/depth{pt}": [] for dp, pt in ARMS}
    best = {}
    # Interleave the reps (arm order inside each rep round) so shared-
    # box drift lands evenly across arms; min-of-reps per arm.
    for _ in range(reps):
        for dp, pt in ARMS:
            key = f"{dp}/depth{pt}"
            rep, wall, h = run_one(smoke, device_prefill=dp == "delta",
                                   pipeline_ticks=pt)
            assert hashes.setdefault(key, h) == h, \
                "same-seed arm reruns diverged"
            walls[key].append(rep["device_ticks_wall_s"])
            if (key not in best or rep["device_ticks_wall_s"]
                    < best[key]["device_ticks_wall_s"]):
                best[key] = rep
    for key, rep in best.items():
        arms[key] = _arm_row(rep)
        arms[key]["loop_walls_s"] = walls[key]

    identical = len(set(hashes.values())) == 1
    delta2, host2 = arms["delta/depth2"], arms["host/depth2"]
    delta1, host1 = arms["delta/depth1"], arms["host/depth1"]
    wall_delta_pct = {
        "depth2": round((delta2["loop_wall_s"] - host2["loop_wall_s"])
                        / host2["loop_wall_s"] * 100.0, 2),
        "depth1": round((delta1["loop_wall_s"] - host1["loop_wall_s"])
                        / host1["loop_wall_s"] * 100.0, 2),
    }
    logical_counters_identical = all(
        a["device_steps"] == delta2["device_steps"]
        and a["device_compiles"] == delta2["device_compiles"]
        and a["evictions"] == delta2["evictions"]
        and a["flow_age_p50"] == delta2["flow_age_p50"]
        and a["flow_audit_ok"]
        for a in arms.values())

    out = {
        "probe": "device_prefill",
        "smoke": smoke,
        "workload": {
            "docs": 24 if smoke else 200, "seed": 7, "engine": "flat",
            "fault_rate": 0.10, "reps_per_arm": reps,
            "basis": "min loop wall (device_ticks_wall_s) per arm; "
                     "logical metrics from the min-wall rep",
        },
        "arms": arms,
        "stream_sha256": hashes,
        "acceptance": {
            "bytes_cut_floor_x": BYTES_CUT_FLOOR_X,
            "wall_regression_bar_pct": WALL_REGRESSION_PCT,
            "streams_sha256_identical": identical,
            "logical_counters_identical": logical_counters_identical,
            "prefill_bytes_cut_x": delta2["prefill_bytes_cut_x"],
            "wall_delta_pct": wall_delta_pct,
            # Smoke walls are sub-second shared-box noise: the wall bar
            # gates only the full-scale (committed) run, like the
            # pipeline probe's smoke tier.
            "pass": bool(
                identical and logical_counters_identical
                and delta2["prefill_bytes_cut_x"] >= BYTES_CUT_FLOOR_X
                and delta1["prefill_bytes_cut_x"] >= BYTES_CUT_FLOOR_X
                and (smoke or max(wall_delta_pct.values())
                     <= WALL_REGRESSION_PCT)
                and delta2["overlap_frac"] > 0.0),
        },
        "note": "CPU run (tier-1 harness): the full-log round trip is "
                "host-memory traffic here, so the wall gate is "
                "parity-within-noise (<=5%); the byte cut and the "
                "removed dispatch-edge device read are the structural "
                "wins, and the silicon re-record (when_up_r14.sh) is "
                "where the hidden-sync removal shows up as overlap. "
                "Logical metrics are seed-deterministic and "
                "platform-independent.",
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default="perf/device_prefill_r16.json")
    a = ap.parse_args()
    out = run_matrix(smoke=a.smoke, reps=a.reps)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out, indent=1))
    if not out["acceptance"]["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

#!/bin/bash
# Round-14 recovery watcher (ISSUE 14 / ROADMAP #1): supersedes
# when_up_r13.sh and keeps its gate chain — matmul tunnel probe ->
# compile pin -> fused kevin device smoke -> pipelined serve device
# smoke (now running the DEVICE-PREFILL delta scatter by default) ->
# sanitized pipelined smoke -> host-vs-delta prefill smoke pair ->
# fused serve-lanes smoke (now PIPELINED depth 2) -> kevin full 5M ->
# the remaining rows via --merge-rows -> the COST LEDGER device
# re-record.  New in r14: the delta-prefill serve smoke runs FIRST as
# its own gate — on a real chip async dispatch is genuinely
# asynchronous, so this is the first run where the removed
# dispatch-edge device read actually buys overlap (on CPU the host
# path's np.array was a formality; on silicon it was a hidden sync) —
# and the host-prefill arm must still converge bit-identically before
# any re-record is trusted.  Safe to re-run; appends to
# perf/when_up_r14.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r14 watcher)" >> perf/when_up_r14.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r14)" >> perf/when_up_r14.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r14.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r14.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r14.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r14.log; exit 1; }
# DEVICE-PREFILL pipelined serve smoke (new in r14): the delta scatter
# + double-buffered tick on real async dispatch — the first run where
# the dispatch edge truly reads no device state.  Convergence + lane
# bit-identity must hold before anything else is trusted.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  >> perf/when_up_r14.log 2>&1 \
  || { echo "device-prefill pipelined serve smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r14.log; exit 1; }
# The HOST-PREFILL arm of the same seed: the two prefill paths must
# stay byte-identical on silicon too (the ISSUE-14 contract the CPU
# suite pins; a divergence here is a chip-side scatter bug).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --host-prefill \
  >> perf/when_up_r14.log 2>&1 \
  || { echo "host-prefill serve smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r14.log; exit 1; }
# SANITIZED pipelined serve device smoke: the aliasing sanitizer under
# real async dispatch.  A failure here is a REAL
# host-write-races-device-step bug the CPU arms could never exhibit.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --sanitize-pipeline \
  >> perf/when_up_r14.log 2>&1 \
  || { echo "SANITIZED pipelined device smoke FAILED rc=$? - aliasing " \
            "race on silicon? NOT re-recording" \
         >> perf/when_up_r14.log; exit 1; }
# Fused serve-lanes loadgen smoke — the blocked mixed kernel's fused
# splice + the serve stack's fused ticks on device; since ISSUE 14 the
# lanes backend PIPELINES at depth 2 (host-mirrored row true-up), so
# this smoke now also exercises its staged sync on real hardware.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r14.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r14.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r14.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r14.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter.
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r14.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r14.log
done
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r14.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r14.log
# And prove the cpu contracts still hold from this very checkout:
# cost ledger + the tcrlint gate (a drifted tree must not re-record).
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r14.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r14.log
timeout 600 env JAX_PLATFORMS=cpu python -m text_crdt_rust_tpu.analysis.lint \
  >> perf/when_up_r14.log 2>&1 \
  || echo "TCRLINT FAILED rc=$? - determinism/schema finding on this checkout" \
       >> perf/when_up_r14.log
echo "$(date -u +%H:%M:%S) r14 re-record done" >> perf/when_up_r14.log

"""Compile-only TPU pin of the production launch geometries.

Run on the real chip (no full replay, no timing):

    python perf/compile_pin.py

AOT-compiles (jit .lower().compile(); nothing executes) every geometry
the committed BENCH_ALL.json depends on — the northstar batch-256 /
block_k-128 / capacity-32768 shape whose silent regression cost r2 40%
of its headline, the config-2 shape, the rle-mixed storm shape, and the
kevin HBM shape.  Exits non-zero naming the first geometry that fails.
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.utils.randedit import make_storm
from text_crdt_rust_tpu.utils.testdata import TestPatch


def pin(name, build):
    t0 = time.time()
    try:
        build()
    except Exception as e:
        print(f"FAIL {name}: {type(e).__name__}: {e}", flush=True)
        return False
    print(f"ok {name} ({time.time() - t0:.1f}s)", flush=True)
    return True


def aot(run_builder):
    """Build a replayer, then AOT-compile its jitted call."""
    run = run_builder()
    # Every make_replayer_* closes over (jitted, staged); reach the pair
    # through the closure to lower without executing.
    cells = {v: c.cell_contents for v, c in
             zip(run.__code__.co_freevars, run.__closure__)}
    jitted = cells["jitted"]
    staged = cells.get("staged")
    tables = cells.get("tables", ())
    args = tuple(staged) + tuple(tables)
    jitted.lower(*args).compile()


def main():
    patches = [TestPatch(0, 0, "seed text here")] + [
        TestPatch(i % 8, 1 if i % 5 == 0 else 0, "ab")
        for i in range(64)
    ]
    merged = B.merge_patches(patches)

    def northstar():
        from text_crdt_rust_tpu.ops import rle as R
        ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
        aot(lambda: R.make_replayer_rle(
            ops, capacity=32768, batch=256, block_k=128, chunk=1024))

    def config2():
        from text_crdt_rust_tpu.ops import rle as R
        ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
        aot(lambda: R.make_replayer_rle(
            ops, capacity=59904, batch=128, block_k=256, chunk=1024))

    def storm():
        from text_crdt_rust_tpu.ops import rle_mixed as RM
        txns, _ = make_storm(4, 10, 4, seed=7)
        table = B.AgentTable(sorted({t.id.agent for t in txns}))
        ops, _ = B.compile_remote_txns(txns, table, lmax=8, dmax=16)
        aot(lambda: RM.make_replayer_rle_mixed(
            ops, capacity=12800, batch=128, block_k=128, chunk=1024))

    def kevin_hbm():
        from text_crdt_rust_tpu.ops import rle_hbm as RH
        ops, _ = B.compile_local_patches(
            [TestPatch(0, 0, " ")] * 64, lmax=1, dmax=None)
        aot(lambda: RH.make_replayer_rle_hbm(
            ops, capacity=10506240, batch=64, block_k=512, chunk=1024))

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    results = [
        pin("northstar b256/k128/cap32768", northstar),
        pin("config2 b128/k256/cap59904", config2),
        pin("rle-mixed storm b128/k128", storm),
        pin("kevin rle-hbm b64/k512/cap10.5M", kevin_hbm),
    ]
    if not all(results):
        sys.exit(1)
    print("all geometries compile", flush=True)


if __name__ == "__main__":
    main()

"""Compile-only TPU pin of the production launch geometries.

Run on the real chip (no full replay, no timing):

    python perf/compile_pin.py

AOT-compiles (jit .lower().compile(); nothing executes) every geometry
the committed BENCH_ALL.json depends on (VERDICT r4 weak #5: the shapes
the headline rows rely on had no standing compile check) — the
northstar default b512/k128/cap20992 (the r5 measured optimum) plus the
b256/b384 shapes at 32768 and 20992, the config-2 measured-capacity
shape, the config-4 storm at the lifted 256-lane width, the kevin HBM
shape exactly as cfg_kevin launches it (b128/k2048, store_origins off),
and the config-5 per-lane engines (local + remote/mixed).  Exits
non-zero naming the first geometry that fails.
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.utils.randedit import make_storm
from text_crdt_rust_tpu.utils.testdata import TestPatch


def pin(name, build):
    t0 = time.time()
    try:
        build()
    except Exception as e:
        print(f"FAIL {name}: {type(e).__name__}: {e}", flush=True)
        return False
    print(f"ok {name} ({time.time() - t0:.1f}s)", flush=True)
    return True


def aot(run_builder):
    """Build a replayer, then AOT-compile its jitted call."""
    run = run_builder()
    # Every make_replayer_* closes over (jitted, staged[, init/tables/
    # deltas]); reach them through the closure to lower without
    # executing.  Call order per engine: staged, then warm-start state
    # (init), then compile-time tables (tables / deltas).
    cells = {v: c.cell_contents for v, c in
             zip(run.__code__.co_freevars, run.__closure__)}
    jitted = cells["jitted"]
    args = tuple(cells.get("staged") or ())
    for extra in ("init", "tables", "deltas"):
        if cells.get(extra) is not None:
            args += tuple(cells[extra])
    jitted.lower(*args).compile()


def main():
    patches = [TestPatch(0, 0, "seed text here")] + [
        TestPatch(i % 8, 1 if i % 5 == 0 else 0, "ab")
        for i in range(64)
    ]
    merged = B.merge_patches(patches)

    def northstar(batch, capacity):
        def build():
            from text_crdt_rust_tpu.ops import rle as R
            ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
            aot(lambda: R.make_replayer_rle(
                ops, capacity=capacity, batch=batch, block_k=128,
                chunk=1024))
        return build

    def config2():
        from text_crdt_rust_tpu.ops import rle as R
        ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
        aot(lambda: R.make_replayer_rle(
            ops, capacity=36096, batch=128, block_k=256, chunk=1024))

    def storm(batch):
        def build():
            from text_crdt_rust_tpu.ops import rle_mixed as RM
            txns, _ = make_storm(4, 10, 4, seed=7)
            table = B.AgentTable(sorted({t.id.agent for t in txns}))
            ops, _ = B.compile_remote_txns(txns, table, lmax=8, dmax=16)
            aot(lambda: RM.make_replayer_rle_mixed(
                ops, capacity=12800, batch=batch, block_k=128,
                chunk=1024))
        return build

    def kevin_hbm():
        # The geometry the committed kevin_tpu row actually uses
        # (cfg_kevin): 128-lane tiles (Mosaic rejects 64-lane HBM-plane
        # slices), block_k=2048, origin outputs dropped.
        from text_crdt_rust_tpu.ops import rle_hbm as RH
        ops, _ = B.compile_local_patches(
            [TestPatch(0, 0, " ")] * 64, lmax=1, dmax=None)
        # capacity = cfg_kevin's formula at kevin_n=5M:
        # ((int(5e6 * 2.1) + 2047) // 2048) * 2048
        aot(lambda: RH.make_replayer_rle_hbm(
            ops, capacity=10500096, batch=128, block_k=2048, chunk=1024,
            store_origins=False))

    def lanes_local():
        # The config-5 local shape: 2048 divergent lanes, tile 512.
        from text_crdt_rust_tpu.ops import rle_lanes as RL
        ops, _ = B.compile_local_patches(merged[:4], lmax=4, dmax=None)
        stacked = B.stack_ops([ops] * 2048)
        aot(lambda: RL.make_replayer_lanes(
            stacked, capacity=1664, chunk=128))

    def lanes_mixed():
        # The config-5 REMOTE shape: 2048 divergent remote lanes,
        # tile 256, run planes + by-order tables, at the FINAL growing
        # capacity the committed cfg5r row records (capacity 2688,
        # order_capacity 3208 — BENCH_ALL.json).
        from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM
        ops, _ = B.compile_local_patches(merged[:4], lmax=4, dmax=None)
        stacked = B.stack_ops([ops] * 2048)
        aot(lambda: RLM.make_replayer_lanes_mixed(
            stacked, capacity=2688, order_capacity=3208,
            chunk=128, lane_tile=256))

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    results = [
        pin("northstar b512/k128/cap20992", northstar(512, 20992)),
        pin("northstar b256/k128/cap32768", northstar(256, 32768)),
        pin("northstar b256/k128/cap20992", northstar(256, 20992)),
        pin("northstar b384/k128/cap20992", northstar(384, 20992)),
        pin("config2 b128/k256/cap36096", config2),
        pin("rle-mixed storm b128/k128", storm(128)),
        pin("rle-mixed storm b256/k128", storm(256)),
        pin("kevin rle-hbm b128/k2048/cap10.5M", kevin_hbm),
        pin("rle-lanes cfg5 b2048/t512/cap1664", lanes_local),
        pin("rle-lanes-mixed cfg5r b2048/t256/cap2688", lanes_mixed),
    ]
    if not all(results):
        sys.exit(1)
    print("all geometries compile", flush=True)


if __name__ == "__main__":
    main()

"""Pipelined-tick probe (ISSUE 12 acceptance): host/device overlap,
serial-vs-pipelined equivalence, and the two serve-loop tuning sweeps
(Nagle emission window, typing lmax) at the 200-doc faulted acceptance
shape.

Four sections of the SAME seeded loadgen (the §14/§16 probe pattern):

- ``pipeline``   — serial (``pipeline_ticks=1``) vs double-buffered
  (``2``) arms, timed (min of ``reps`` loop walls).  The pipelined arm
  must show ``overlap_frac > 0`` (device-sync demand hidden under host
  work) WITHOUT regressing the serial loop wall > 5%; two untimed
  ``trace_keep`` runs additionally pin that the two modes emit
  **byte-identical logical streams** (flow events included) and
  identical flow audits/op-age distributions — pipelining moves wall
  time only.
- ``nagle``      — the §16 latency lever: sweep the columnar-wire
  emission window (``nagle_txns``/``nagle_rounds``) at full flow
  sampling and read clean-remote op-age (emission-to-frame batching
  dominates it) against the bytes/op cost of smaller batches.  The
  shipped ServeConfig default must cut clean-remote p50 from the old
  64-txn window's ~12 ticks to <= 6.
- ``lmax``       — the typing-workload step-economy lever (the PR-6
  fusion cap): sweep ``ServeConfig.lmax`` over 8/16/32 on ``--workload
  typing`` and record device steps, ops/step and loop wall; the
  shipped default is the sweep winner.
- ``defaults``   — one run at the exact shipped ServeConfig, asserting
  the acceptance numbers hold at the defaults users get.

Logical metrics (ages, steps, bytes) are seed-deterministic; wall
numbers carry shared-box noise and gate only the 5% regression bar.
Writes ``perf/pipeline_r14.json``.

Run: python perf/pipeline_probe.py [--smoke] [--reps N] [--out PATH]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

WALL_REGRESSION_PCT = 5.0
CLEAN_P50_FLOOR_TICKS = 6
# (nagle_txns, nagle_rounds) arms: the first approximates the pre-ISSUE-12
# behavior (64 txns / 6 resync windows x resync_every=4 ticks), the rest
# walk the window down to near-per-event emission.
NAGLE_ARMS = ((64, 24), (64, 6), (32, 8), (16, 4), (16, 2), (8, 2),
              (4, 1))
NAGLE_ARMS_SMOKE = ((64, 24), (16, 2), (4, 1))
LMAX_ARMS = (8, 16, 32)


def run_one(smoke: bool, *, pipeline_ticks=None, nagle=None, lmax=None,
            workload="scatter", flow_mod=1, keep_trace=False, seed=7):
    """One seeded loadgen run; returns (report, wall_s, logical_trace)."""
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    kw = {}
    if pipeline_ticks is not None:
        kw["pipeline_ticks"] = pipeline_ticks
    if nagle is not None:
        kw["nagle_txns"], kw["nagle_rounds"] = nagle
    if lmax is not None:
        kw["lmax"] = lmax
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=16,
                      flow_sample_mod=flow_mod, trace_keep=keep_trace,
                      **kw)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                       events_per_tick=events, zipf_alpha=1.1,
                       fault_rate=0.10, local_prob=0.25, seed=seed,
                       cfg=cfg, workload=workload)
    t0 = time.perf_counter()
    rep = gen.run()
    wall = time.perf_counter() - t0
    assert rep["converged"], rep["mismatches"][:4]
    trace = gen.server.tracer.logical_bytes() if keep_trace else None
    return rep, wall, trace


def _age_row(rep: dict) -> dict:
    f = rep["flow"]
    w = rep["wire"]
    return {
        "audit_ok": f["audit_ok"],
        "age_p50": f["ages_ticks"]["p50"],
        "age_p99": f["ages_ticks"]["p99"],
        "clean_p50": f["by_class"]["clean"]["p50"],
        "clean_p99": f["by_class"]["clean"]["p99"],
        "redelivered_p50": f["by_class"]["redelivered"]["p50"],
        "bytes_per_op": w["bytes_per_op"],
        "push_bytes": w["push_bytes"],
        "pull_bytes": w["pull_bytes"],
    }


def run_matrix(smoke: bool = False, reps: int = 2) -> dict:
    # -- 1. pipeline: serial vs double-buffered, timed -------------------
    pipeline = {}
    loops = {}
    for name, pt in (("serial", 1), ("pipelined", 2)):
        best = None
        for _ in range(reps):
            rep, wall, _ = run_one(smoke, pipeline_ticks=pt,
                                   flow_mod=16)
            if (best is None or rep["device_ticks_wall_s"]
                    < best["device_ticks_wall_s"]):
                best = rep
        # Report the WHOLE min-wall rep, so loop_wall_s and its
        # overlap/stall/tick metrics all come from one execution (a
        # min-of-walls paired with another rep's overlap would mix
        # runs in the committed artifact).
        loops[name] = best["device_ticks_wall_s"]
        pipeline[name] = {
            "pipeline_ticks": best["pipeline"]["ticks"],
            "loop_wall_s": round(loops[name], 3),
            "overlap_frac": best["pipeline"]["overlap_frac"],
            "stall_ms_total": best["pipeline"]["stall_ms_total"],
            "tick_p50_ms": best["tick_ms"]["p50"],
            "tick_p99_ms": best["tick_ms"]["p99"],
        }
    wall_delta_pct = round(
        (loops["pipelined"] - loops["serial"]) / loops["serial"] * 100.0,
        2)

    # Byte-identity across modes (untimed, full sampling + retention):
    # the logical stream INCLUDING flow spans must not know whether the
    # barrier was deferred.
    rep_s, _, tr_s = run_one(smoke, pipeline_ticks=1, keep_trace=True)
    rep_p, _, tr_p = run_one(smoke, pipeline_ticks=2, keep_trace=True)
    identical = tr_s == tr_p
    flow_identical = (rep_s["flow"]["ages_ticks"] ==
                      rep_p["flow"]["ages_ticks"]
                      and rep_s["flow"]["spans"] == rep_p["flow"]["spans"]
                      and rep_s["flow"]["audit_ok"]
                      and rep_p["flow"]["audit_ok"])

    # -- 2. nagle sweep (logical metrics are seed-deterministic) ---------
    nagle = {}
    for arm in (NAGLE_ARMS_SMOKE if smoke else NAGLE_ARMS):
        rep, wall, _ = run_one(smoke, nagle=arm)
        nagle[f"{arm[0]}/{arm[1]}"] = {
            **_age_row(rep), "loop_wall_s": rep["device_ticks_wall_s"]}

    # -- 3. lmax sweep on the typing workload ----------------------------
    lmax = {}
    for lm in LMAX_ARMS:
        rep, wall, _ = run_one(smoke, lmax=lm, workload="typing",
                               flow_mod=16)
        lmax[str(lm)] = {
            "steps_total": rep["tick_ms"]["steps_total"],
            "steps_prefuse": rep["tick_ms"]["steps_prefuse"],
            "ops_per_step": rep["tick_ms"]["ops_per_step"],
            "device_steps_padded": rep["server"].get("device_steps", 0),
            "bytes_per_op": rep["wire"]["bytes_per_op"],
            "loop_wall_s": rep["device_ticks_wall_s"],
        }

    # -- 4. the shipped defaults -----------------------------------------
    d = ServeConfig()
    rep_def, _, _ = run_one(smoke)
    defaults = {
        "pipeline_ticks": d.pipeline_ticks,
        "nagle_txns": d.nagle_txns,
        "nagle_rounds": d.nagle_rounds,
        "lmax": d.lmax,
        **_age_row(rep_def),
        "overlap_frac": rep_def["pipeline"]["overlap_frac"],
    }

    baseline_key = "64/24"
    out = {
        "probe": "pipelined_tick",
        "smoke": smoke,
        "workload": {
            "docs": rep_def["docs"], "seed": 7, "engine": "flat",
            "fault_rate": 0.10, "reps_per_timed_arm": reps,
            "basis": "min loop wall (device_ticks_wall_s) per arm",
        },
        "pipeline": {
            **pipeline,
            "wall_delta_pct": wall_delta_pct,
            "logical_streams_byte_identical": identical,
            "flow_reports_identical": flow_identical,
        },
        "nagle_sweep": nagle,
        "lmax_sweep": lmax,
        "defaults": defaults,
        "acceptance": {
            "wall_regression_bar_pct": WALL_REGRESSION_PCT,
            "clean_p50_floor_ticks": CLEAN_P50_FLOOR_TICKS,
            "clean_p50_before": nagle.get(baseline_key, {}).get(
                "clean_p50"),
            "clean_p50_shipped": defaults["clean_p50"],
            "pass": bool(
                identical and flow_identical
                and pipeline["pipelined"]["overlap_frac"] > 0.0
                and wall_delta_pct <= WALL_REGRESSION_PCT
                and defaults["audit_ok"]
                and defaults["clean_p50"] <= CLEAN_P50_FLOOR_TICKS),
        },
        "note": "CPU run (tier-1 harness): XLA CPU saturates the cores, "
                "so the overlap window mostly hides dispatch/sync "
                "latency rather than buying wall — the bar here is "
                "overlap>0 at <=5% wall cost; the silicon re-record "
                "(perf/when_up_r12.sh) measures the real hidden device "
                "time.  Logical metrics (ages, steps, bytes) are "
                "seed-deterministic and platform-independent.",
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default="perf/pipeline_r14.json")
    a = ap.parse_args()
    out = run_matrix(smoke=a.smoke, reps=a.reps)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out, indent=1))
    if not out["acceptance"]["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

#!/bin/bash
# Round-16 tick-train watcher (ISSUE 20 / dispatch amortization):
# supersedes when_up_r15.sh and keeps its gate chain — matmul tunnel
# probe -> compile pin -> fused kevin device smoke -> device-prefill
# pipelined serve smoke -> host-prefill arm -> sanitized pipelined
# smoke -> journaled smoke -> crash/recover smoke -> fused serve-lanes
# smoke -> kevin full 5M -> remaining rows -> cost-ledger device
# re-record.  New in r16: TICK-TRAIN device smokes (depth 2 and 4) run
# before any re-record is trusted — T ticks' op tensors replayed as ONE
# lax.scan program on real async dispatch.  On CPU the train matrix is
# tier-1-proven (PERF.md §22: sha-identical streams, 3.77x dispatch cut
# at depth 4); on silicon it is the first time the T-for-one launch
# amortization meets real dispatch latency, which is the entire point
# of the feature — the CPU wall gate is parity-within-noise, the chip
# is where the cut should become wall.  Safe to re-run; appends to
# perf/when_up_r16.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r16 watcher)" >> perf/when_up_r16.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r16)" >> perf/when_up_r16.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r16.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r16.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r16.log; exit 1; }
# DEVICE-PREFILL pipelined serve smoke: the delta scatter +
# double-buffered tick on real async dispatch.  Convergence + lane
# bit-identity must hold before anything else is trusted.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "device-prefill pipelined serve smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r16.log; exit 1; }
# TICK-TRAIN device smokes (new in r16): depth 2 then depth 4 — the
# outer-scan train program, the concatenated prefill scatter, the
# device-accumulated overflow flag and its non-blocking drain
# (jax.Array.is_ready), all under real async dispatch for the first
# time.  Convergence + lane bit-identity gate; a failure here is a
# train-scheduler bug the CPU arms could not exhibit (e.g. a flag
# drain racing genuinely-async dispatch).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --train-ticks 2 \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "depth-2 tick-train device smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r16.log; exit 1; }
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --train-ticks 4 \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "depth-4 tick-train device smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r16.log; exit 1; }
# The HOST-PREFILL arm of the same seed: the two prefill paths must
# stay byte-identical on silicon too (the ISSUE-14 contract the CPU
# suite pins; a divergence here is a chip-side scatter bug).
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --host-prefill \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "host-prefill serve smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r16.log; exit 1; }
# SANITIZED pipelined serve device smoke: the aliasing sanitizer under
# real async dispatch.  A failure here is a REAL
# host-write-races-device-step bug the CPU arms could never exhibit.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 --sanitize-pipeline \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "SANITIZED pipelined device smoke FAILED rc=$? - aliasing " \
            "race on silicon? NOT re-recording" \
         >> perf/when_up_r16.log; exit 1; }
# JOURNALED pipelined device smoke: the write-ahead journal appending
# at the admission edge while real async device steps are in flight.
# The journal is host-side and logically invisible by construction —
# this proves it stays that way when dispatch is genuinely
# asynchronous (convergence gate; the journal fsyncs every tick).
rm -rf /tmp/tcr_r16_journal && mkdir -p /tmp/tcr_r16_journal
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --pipeline-ticks 2 \
  --journal-dir /tmp/tcr_r16_journal --journal-fsync-ticks 1 \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "JOURNALED pipelined device smoke FAILED rc=$? - NOT " \
            "re-recording" >> perf/when_up_r16.log; exit 1; }
# CRASH/RECOVER device smoke: kill post-dispatch with a depth-2
# pipeline in flight, recover a FRESH server from the journal (replay
# through the normal admission path, re-derive the crashed tick),
# resume the workload, and byte-compare logical streams against the
# uncrashed same-seed twin — the PERF.md §21 contract on real
# hardware.  Exit 1 = digests differ or a crash-boundary flow audit
# finding; NOT re-recording on that.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 16 --ticks 10 --crash-at post-dispatch:5 \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "device CRASH/RECOVER smoke FAILED rc=$? - recovery " \
            "divergence on silicon? NOT re-recording" \
         >> perf/when_up_r16.log; exit 1; }
# Fused serve-lanes loadgen smoke — the blocked mixed kernel's fused
# splice + the serve stack's fused ticks on device; the lanes backend
# PIPELINES at depth 2 (host-mirrored row true-up), so this smoke
# also exercises its staged sync on real hardware.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r16.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r16.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r16.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r16.log
# Remaining rows, most verdict-critical first; every merged row is
# ledger_version-stamped by the exporter.  The serve row now ships
# train_ticks=2 (its train/dispatch ride-alongs land on silicon here).
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r16.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r16.log
done
# The train probe at full scale on silicon: the committed CPU record
# (perf/train_r17.json) pins sha-identity + the dispatch cut; the
# device run is where the cut becomes wall.  Writes a SEPARATE file —
# the CPU record stays the tier-1 reference.
timeout 3600 python perf/train_probe.py --device \
  --out perf/train_r17_device.json \
  >> perf/when_up_r16.log 2>&1 \
  || echo "device train probe FAILED rc=$?" >> perf/when_up_r16.log
# The cost-ledger silicon cells: device-step wall histograms +
# real-HLO costs + the flow-device per-op provenance cell, appended to
# the committed ledger (cpu cells untouched).
timeout 3600 python perf/cost_ledger_probe.py --device \
  >> perf/when_up_r16.log 2>&1 \
  || echo "ledger device re-record FAILED rc=$?" >> perf/when_up_r16.log
# And prove the cpu contracts still hold from this very checkout:
# cost ledger (now including the train dispatch-economy metrics) + the
# tcrlint gate (a drifted tree must not re-record).
timeout 1800 env JAX_PLATFORMS=cpu python bench.py --check-ledger \
  >> perf/when_up_r16.log 2>&1 \
  || echo "LEDGER CHECK FAILED rc=$? - cpu cost contract drifted" \
       >> perf/when_up_r16.log
timeout 600 env JAX_PLATFORMS=cpu python -m text_crdt_rust_tpu.analysis.lint \
  >> perf/when_up_r16.log 2>&1 \
  || echo "TCRLINT FAILED rc=$? - determinism/schema finding on this checkout" \
       >> perf/when_up_r16.log
echo "$(date -u +%H:%M:%S) r16 re-record done" >> perf/when_up_r16.log

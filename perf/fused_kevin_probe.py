"""Step-cost probe for the fused split-batch prepare (ISSUE 5
acceptance): the kevin prepend workload at smoke scale on CPU
interpret, fused vs unfused, on BOTH fused engines.

Proves, per engine:
- device-step count reduced >= 8x at EQUAL workload (the acceptance
  floor; at the bench width W=64 the reduction is 64x),
- fused output bit-identical to the unfused engine AND the analytic
  oracle (``expand_runs`` full order sequence: prepends reverse
  insertion order, so the doc must read orders N-1..0),
- the by-order logs (origins/ranks/chars via ``rle_to_flat``) match
  the unfused stream's exactly — the fused rows bake in origin chains
  the unfused path derives step-by-step.

Writes ``perf/fused_kevin_r8.json`` including the compile-time step
table for the full 5M silicon workload (re-recorded on tunnel recovery
by ``perf/when_up_r8.sh``).

Run: python perf/fused_kevin_probe.py [--n 4096] [--fuse-w 64]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import rle as R  # noqa: E402
from text_crdt_rust_tpu.ops import rle_hbm as RH  # noqa: E402
from text_crdt_rust_tpu.utils.testdata import TestPatch  # noqa: E402


def probe_engine(name, make, ops_u, ops_f, n, kw):
    want = np.arange(n, 0, -1, dtype=np.int32)
    t0 = time.perf_counter()
    res_u = make(ops_u, **kw)
    wall_u = time.perf_counter() - t0
    t0 = time.perf_counter()
    res_f = make(ops_f, **kw)
    wall_f = time.perf_counter() - t0
    eu, ef = R.expand_runs(res_u), R.expand_runs(res_f)
    assert np.array_equal(eu, ef), f"{name}: fused diverged from unfused"
    assert np.array_equal(ef, want), f"{name}: diverged from the oracle"
    du = R.rle_to_flat(ops_u, res_u)
    df = R.rle_to_flat(ops_f, res_f)
    for fld in ("signed", "ol_log", "or_log", "rank_log", "chars_log",
                "n", "next_order"):
        assert np.array_equal(np.asarray(getattr(du, fld)),
                              np.asarray(getattr(df, fld))), (name, fld)
    return {
        "engine": name,
        "steps_unfused": ops_u.num_steps,
        "steps_fused": ops_f.num_steps,
        "step_reduction_x": round(ops_u.num_steps / ops_f.num_steps, 2),
        "bit_identical_expand_runs": True,
        "bit_identical_order_logs": True,
        "oracle_equal": True,
        "interpret_wall_s":
            {"unfused": round(wall_u, 2), "fused": round(wall_f, 2)},
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--fuse-w", type=int, default=64)
    ap.add_argument("--out", default="perf/fused_kevin_r8.json")
    args = ap.parse_args()
    n, w = args.n, args.fuse_w
    patches = [TestPatch(0, 0, " ")] * n
    ops_u, _ = B.compile_local_patches(patches, lmax=w)
    ops_f, _ = B.compile_local_patches(patches, lmax=w, fuse_w=w)
    block_k = 256
    cap = ((int(n * 2.1) + block_k - 1) // block_k) * block_k
    kw = dict(capacity=cap, batch=8, block_k=block_k, chunk=128,
              interpret=True)
    rows = [
        probe_engine("rle-hbm", RH.replay_local_rle_hbm, ops_u, ops_f,
                     n, kw),
        probe_engine("rle", R.replay_local_rle, ops_u, ops_f, n, kw),
    ]
    full_n = 5_000_000
    out = {
        "workload": {"n": n, "fuse_w": w, "shape":
                     "kevin single-char prepends (benches/yjs.rs:51-62)"},
        "geometry": {k: v for k, v in kw.items() if k != "interpret"},
        "engines": rows,
        "full_scale_step_table": {
            "n": full_n,
            "steps_unfused": full_n,
            "steps_fused_w64": -(-full_n // 64),
            "step_reduction_x": 64.0,
            "note": "compile-time arithmetic for the 5M silicon "
                    "workload; wall re-record armed in "
                    "perf/when_up_r8.sh",
        },
        "acceptance": {
            "floor_x": 8,
            "measured_x": min(r["step_reduction_x"] for r in rows),
            "pass": all(r["step_reduction_x"] >= 8 for r in rows),
        },
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print(f"wrote {args.out}; acceptance "
          f"{'PASS' if out['acceptance']['pass'] else 'FAIL'}",
          file=sys.stderr)
    return 0 if out["acceptance"]["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

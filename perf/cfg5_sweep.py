"""Config-5 lane-tile / capacity sweep (post-recovery tuning).

The r5 probe (`perf/cfg5_probe.py`) showed ~30% run-to-run variance on
identical kernels and an untuned lane tile.  Sweep T x capacity, two
compiles each (variance estimate), one cfg5-shaped chunk (100 steps x
2048 divergent lanes).

    python perf/cfg5_sweep.py
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from text_crdt_rust_tpu.ops import rle_lanes as RL
from perf.cfg5_probe import build_cfg5_stacked


def main():
    n_docs, steps = 2048, 100
    stacked = build_cfg5_stacked(n_docs, steps)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    best = (None, 1e9)
    for cap in (1024, 1664):
        for tile in (256, 512, 1024):
            for trial in (1, 2):
                RL._build_call.cache_clear()
                try:
                    run = RL.make_replayer_lanes(
                        stacked, capacity=cap, chunk=128,
                        lane_tile=tile)
                    np.asarray(run().err)
                except Exception as e:
                    print(f"cap={cap} T={tile}: FAIL "
                          f"{type(e).__name__}: {str(e)[:120]}",
                          flush=True)
                    break
                t0 = time.perf_counter()
                for _ in range(5):
                    res = run()
                np.asarray(res.err)
                dt = (time.perf_counter() - t0) / 5
                print(f"cap={cap} T={tile} trial{trial}: "
                      f"{dt * 1e3:.1f}ms/chunk "
                      f"({dt / steps * 1e6:.0f}us/step)", flush=True)
                if dt < best[1]:
                    best = ((cap, tile), dt)
    print(f"best: cap,T={best[0]} {best[1] * 1e3:.1f}ms/chunk",
          flush=True)


if __name__ == "__main__":
    main()

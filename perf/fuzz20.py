import os, random, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/root/repo/tests")
from text_crdt_rust_tpu.ops import batch as B, flat as F, rle as R
from text_crdt_rust_tpu.ops import rle_hbm as RH, rle_lanes as RL
from text_crdt_rust_tpu.ops import span_arrays as SA
from test_device_flat import random_patches

fails = 0
for seed in range(100, 120):
    rng = random.Random(seed)
    patches, content = random_patches(rng, 60)
    merged = B.merge_patches(patches)
    lmax = max([len(p.ins_content) for p in merged] + [1])
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    ref = F.apply_ops(SA.make_flat_doc(512),
                      B.compile_local_patches(patches, lmax=8, dmax=None)[0])
    want = SA.to_string(ref)
    assert want == content
    r1 = R.replay_local_rle(ops, capacity=256, batch=8, block_k=8,
                            chunk=64, interpret=True)
    r2 = RH.replay_local_rle_hbm(ops, capacity=256, batch=8, block_k=8,
                                 chunk=64, interpret=True)
    stacked = B.stack_ops([ops] * 4)
    r3 = RL.replay_lanes(stacked, capacity=256, chunk=16, interpret=True)
    ok = (SA.to_string(R.rle_to_flat(ops, r1)) == want
          and SA.to_string(R.rle_to_flat(ops, r2)) == want
          and SA.to_string(RL.lanes_to_flat(stacked, r3, 2)) == want)
    if not ok:
        fails += 1
        print(f"seed {seed}: DIVERGED", flush=True)
print(f"fuzz: 20 seeds x 3 engines, {fails} failures", flush=True)

"""Deterministic cost-ledger probe (ISSUE 10 tentpole, part 2).

Derives the ``perf/COST_LEDGER.json`` cells at small PINNED
deterministic shapes and (record mode) commits them.  Every cpu-cell
metric is a pure function of the seeded workload — the same
logical-first discipline that makes two same-seed loadgen runs emit
byte-identical traces (PERF.md §14) — so ``bench.py --check-ledger``
can re-derive the cells on any box, wall-clock-free, and fail with a
named per-metric diff on drift.

Cells (kind ``cpu`` — the tier-1 gate re-derives all of them):

- ``serve``        — the small seeded flat-engine loadgen (the
  `test_obs_trace.small_loadgen_run` shape): device steps pre/post
  fusion, recompiles, wire bytes by lane + bytes/op, checkpoint bytes
  per evict kind, admission/codec rejects, trace volume — PLUS the
  static compiled-HLO cost of the flat serve kernel at every step
  bucket (flops / bytes accessed via ``lower().compile()
  .cost_analysis()``, collectives asserted 0 on the single-shard
  serve), generalizing the ``sp`` 124-collectives count to the serve
  engine×bucket grid;
- ``serve-lanes``  — the SAME seeded tick trace replayed through the
  kernel-exact blocked-lanes cost model (``perf/blocked_lanes_sim``):
  touched rows/step blocked vs flat, pass traffic, splits, hint
  misses — the O(NB+K) contract as a committed number (the real
  lanes-backend run costs ~90 s of pallas-interpret compile, so the
  gate replays the flat run's bit-identical compiled streams instead;
  `perf/serve_lanes_r7.json` holds the full-scale proof);
- ``fused-trace``  — ``ops.batch.fuse_steps`` over a pinned
  automerge-paper prefix compiled at the serve lmax: steps in/out,
  rows saved, per-shape fusion counts;
- ``sp``           — the sequence-parallel engine's static ICI cost
  model at a tiny pinned shape: collectives/step by kind off the
  compiled HLO (the 124 = 94 all-reduce + 30 all-gather invariant),
  flops/bytes banded;
- ``flow``         — per-op provenance (ISSUE 11): the same small
  loadgen at FULL flow sampling — span terminal-state census
  (conservation audit asserted green before pinning) and
  op-age-at-apply percentiles in exact logical ticks, the ROADMAP-7
  pipelined-tick before/after latency contract;
- ``recovery``     — durability (ISSUE 16): the pinned post-dispatch
  crash scenario (kill at a seeded tick with the depth-2 pipeline in
  flight, recover from the journal, resume) — byte-identity to the
  uncrashed twin and both crash-boundary conservation audits asserted
  green BEFORE pinning; metrics are the journal byte bill (bytes/op,
  vs the wire bill — the full-input-log floor, PERF.md §21) and the
  replay economy (records / ops / ticks-to-recover, all logical);
- ``flash-crowd``  — one hot doc takes 90% of traffic from a seeded
  tick on (ISSUE 16 satellite): survives at pinned cost — lane
  overflow degrades to the host oracle (counted, never an assert),
  eviction/restore thrash pinned, convergence asserted.

``--device`` (perf/when_up_r11.sh) appends the silicon cells — wall
histograms + real-HLO costs on the default backend, plus the flow
cell's device variant (logical ages must reproduce EXACTLY on chip) —
without touching the cpu cells; the gate skips ``kind: device`` cells
on CPU.

Run:  python perf/cost_ledger_probe.py [--out perf/COST_LEDGER.json]
                                       [--cells a,b] [--device]
Check: python bench.py --check-ledger
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The sp cell's virtual mesh needs the host-device count baked in
# before the CPU client initializes (the sp_bench pattern).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from text_crdt_rust_tpu.obs.ledger import (  # noqa: E402
    LEDGER_PATH,
    LEDGER_SCHEMA_VERSION,
    metric,
    validate_ledger,
)

# -- pinned workload shapes ---------------------------------------------------
# Changing ANY of these is a ledger re-record, not a tweak: the
# committed values are only comparable at these exact shapes.

SEED = 7
SMALL_LOADGEN = dict(docs=6, agents_per_doc=2, ticks=6,
                     events_per_tick=12, zipf_alpha=1.1, fault_rate=0.10,
                     local_prob=0.25, seed=SEED)
SERVE_SHAPE = dict(num_shards=1, lanes_per_shard=4)
SERVE_TRAIN_TICKS = 2  # the serve cell rides a depth-2 tick train
#                        (ISSUE 20) so the pinned dispatch metrics are
#                        nontrivial — every OTHER serve metric must
#                        still match the serial record bit for bit
#                        (train length is a wall-clock-only knob)
FUSED_TRACE = "automerge-paper"
FUSED_PATCHES = 4000
from text_crdt_rust_tpu.config import ServeConfig as _ServeConfig  # noqa: E402

FUSED_LMAX = _ServeConfig().lmax  # the ServeConfig default (16 since
#                    the ISSUE-12 typing-lmax sweep) — ONE source of
#                    truth with the HLO cell's backend, so a future
#                    default change re-records both cells together
#                    instead of drifting them apart
FUSED_W = 8
SP_PATCHES = 120
SP_SHARD_ROWS = 64
HLO_BUCKETS = (8, 32)   # ServeConfig.step_buckets prefix (128 adds ~s
#                         of compile for no extra information)
HLO_TOL = 0.5           # HLO costs drift with compiler versions
WALL_TOL = 1.0          # device-cell wall bands (informational)

_COLLECTIVE_RE = re.compile(
    r"all-gather|all_gather|all-reduce|all_reduce|collective-permute|"
    r"collective_permute|all-to-all|all_to_all", re.IGNORECASE)

CPU_CELLS = ("serve", "serve-lanes", "fused-trace", "sp", "flow",
             "recovery", "flash-crowd")

#: The recovery cell's crash shape: two shards (TICK-marker duplication
#: in play) under eviction pressure, killed post-dispatch mid-run.
CHAOS_SHAPE = dict(num_shards=2, lanes_per_shard=2)
CHAOS_CRASH_TICK = 3
#: Flash-crowd shape: lanes far smaller than the crowd's appetite so
#: the hot doc forces overflow-degrade + residency thrash.
FLASH_TICK = 2
FLASH_DOC = 1
FLASH_SHAPE = dict(num_shards=1, lanes_per_shard=2)


def _force_cpu():
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # in-process import after backend init (tier-1 harness)


def _hlo_cost(lowered) -> dict:
    """(collectives, flops, bytes accessed) of one lowered computation
    — compiled text for the collective count, ``cost_analysis()`` for
    flops/bytes (a list of per-computation dicts on some jax versions).
    """
    compiled = lowered.compile()
    try:
        text = compiled.as_text()
    except Exception:
        text = lowered.as_text()
    hits = _COLLECTIVE_RE.findall(text)
    kinds = {}
    for h in hits:
        k = h.lower().replace("_", "-")
        kinds[k] = kinds.get(k, 0) + 1
    ca = compiled.cost_analysis()
    d = ca[0] if isinstance(ca, list) else (ca or {})
    return {"collectives": len(hits), "by_kind": kinds,
            "flops": float(d.get("flops", 0.0)),
            "bytes": float(d.get("bytes accessed", 0.0))}


def _hlo_flat_metrics(platform_note: str = "cpu") -> dict:
    """Static compiled-HLO cost of the flat serve kernel at each step
    bucket (lanes/capacities pinned to SERVE_SHAPE's backend)."""
    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.ops import flat as F
    from text_crdt_rust_tpu.serve.batcher import FlatLaneBackend

    backend = FlatLaneBackend(lanes=SERVE_SHAPE["lanes_per_shard"],
                              capacity=512, order_capacity=1536,
                              lmax=FUSED_LMAX)
    out = {}
    for s_bkt in HLO_BUCKETS:
        stacked = B.stack_ops(
            [B.pad_ops(B.empty_ops(FUSED_LMAX), s_bkt)
             for _ in range(backend.lanes)])
        lowered = F._apply_ops_batch.lower(backend.docs, stacked,
                                           local_only=False)
        cost = _hlo_cost(lowered)
        out[f"hlo_flat_b{s_bkt}_flops"] = metric(
            cost["flops"], "hlo", tol=HLO_TOL)
        out[f"hlo_flat_b{s_bkt}_bytes"] = metric(
            cost["bytes"], "hlo", tol=HLO_TOL)
        # Single-shard serving must stay collective-free — an exact 0.
        out[f"hlo_flat_b{s_bkt}_collectives"] = metric(
            cost["collectives"], "hlo")
    return out


def cell_serve_pair():
    """ONE seeded small loadgen run feeding two cells: the ``serve``
    logical-cost cell (from the server's registry + the loadgen report)
    and the ``serve-lanes`` touched-rows cell (the run's compiled tick
    streams replayed through the kernel-exact blocked cost model, sims
    re-seeded from the oracle at every residency upload exactly as the
    device backend is)."""
    import blocked_lanes_sim as BLS

    from text_crdt_rust_tpu.config import ServeConfig, lane_block_geometry
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    base = ServeConfig()
    K = base.lanes_block_k
    cap_runs, NB, NBT = lane_block_geometry(base.lane_capacity, K)
    OCAP = base.order_capacity

    cfg = ServeConfig(engine="flat", train_ticks=SERVE_TRAIN_TICKS,
                      **SERVE_SHAPE)
    gen = ServeLoadGen(cfg=cfg, **SMALL_LOADGEN)

    c = BLS.Counter()
    unb = BLS.UnblockedCost(base.lane_capacity)
    sims = {}

    def tap(doc_id, ops):
        sim = sims.get(doc_id)
        if sim is None:
            sim = sims[doc_id] = BLS.BlockedLaneSim(K, cap_runs, c, OCAP)
        BLS._replay_stream(sim, unb, c, ops)

    gen.server.batcher.step_trace = tap
    res = gen.server.residency
    for si, backend in enumerate(res.backends):
        def wrap(orig, si):
            def upload(b, oracle, ranks):
                doc_id = res.lane_owner[si][b]
                sim = sims.get(doc_id)
                if sim is None:
                    sim = sims[doc_id] = BLS.BlockedLaneSim(
                        K, cap_runs, c, OCAP)
                BLS._seed_sim_from_oracle(sim, oracle)
                orig(b, oracle, ranks)
            return upload
        backend.upload_lane = wrap(backend.upload_lane, si)

    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]

    tick = rep["tick_ms"]
    srv = rep["server"]
    wire = rep["wire"]

    m = {
        # steps: the device-step economy of the tick loop.
        "item_ops_applied": metric(rep["item_ops_applied"], "steps"),
        "steps_total": metric(tick["steps_total"], "steps"),
        "steps_prefuse": metric(tick["steps_prefuse"], "steps"),
        "fused_rows_saved": metric(tick["fused_rows_saved"], "steps"),
        "device_ticks": metric(srv.get("device_ticks", 0), "steps"),
        "device_steps_padded": metric(srv.get("device_steps", 0),
                                      "steps"),
        # compile: steady state must cycle a fixed kernel set.
        "device_compiles": metric(srv.get("device_compiles", 0),
                                  "compile"),
        # train (ISSUE 20): the tick-train dispatch economy at the
        # pinned depth-2 train.  Dispatch counts are logical (same-seed
        # deterministic; partial flushes land at seeded residency
        # boundaries), so they pin exactly in the "steps" family —
        # another named-diff guard: a scheduler change that silently
        # flushes trains shows up here as a dispatch regression.
        "device_dispatches": metric(tick.get("device_dispatches", 0),
                                    "steps"),
        "device_dispatches_per_tick": metric(
            tick.get("device_dispatches_per_tick", 0.0), "steps"),
        "train_len": metric(tick.get("train_len", 0.0), "steps"),
        # prefill (ISSUE 14): the device-resident log path's byte
        # economy — scatter-delta bytes vs the full-log round trip the
        # host path would move, the un-padded scatter volume, and the
        # scatter program's own compile count (bounded by the
        # geometric bucket series).  Bytes metrics live in the "wire"
        # (bytes) family and the compile count in "compile" — the
        # existing families cover them, so no METRIC_FAMILIES growth
        # (and no LEDGER_SCHEMA_VERSION bump invalidating committed
        # bench rows).
        "prefill_bytes_per_tick": metric(
            tick.get("prefill_bytes_per_tick", 0.0), "wire"),
        "prefill_bytes_cut_x": metric(
            tick.get("prefill_bytes_cut_x", 0.0), "wire"),
        "prefill_scatter_len": metric(
            tick.get("prefill_scatter_len", 0), "wire"),
        "prefill_scatter_compiles": metric(
            tick.get("prefill_scatter_compiles", 0), "compile"),
        # wire: the replication byte bill by lane.
        "wire_push_bytes": metric(wire["push_bytes"], "wire"),
        "wire_pull_bytes": metric(wire["pull_bytes"], "wire"),
        "wire_ctrl_bytes": metric(wire["ctrl_bytes"], "wire"),
        "wire_txn_bytes": metric(wire["txn_bytes"], "wire"),
        "ops_replicated": metric(wire["ops_replicated"], "wire"),
        "bytes_per_op": metric(wire["bytes_per_op"], "wire"),
        # ckpt: eviction residency costs by kind.
        "evictions": metric(srv.get("evictions", 0), "ckpt"),
        "restores": metric(srv.get("restores", 0), "ckpt"),
        "ckpt_bytes_written": metric(srv.get("ckpt_bytes_written", 0),
                                     "ckpt"),
        "ckpt_saves_delta": metric(srv.get("ckpt_saves_delta", 0),
                                   "ckpt"),
        "ckpt_saves_full": metric(srv.get("ckpt_saves_full", 0), "ckpt"),
        "ckpt_bytes_per_evict_mean": metric(
            srv.get("ckpt_bytes_per_evict_mean", 0.0), "ckpt"),
        # admission: typed-refusal economy under 10% faults.
        "admitted": metric(srv.get("admitted", 0), "admission"),
        "admitted_items": metric(srv.get("admitted_items", 0),
                                 "admission"),
        "rejected_frame_rejected": metric(
            srv.get("rejected_frame_rejected", 0), "admission"),
        "codec_failures": metric(srv.get("obs_failures_codec", 0),
                                 "admission"),
        # trace: event volume + bundle economy (bounded by design).
        "trace_events": metric(rep["obs"]["trace_events"], "trace"),
        "bundles_written": metric(rep["obs"]["bundles_written"],
                                  "trace"),
        "bundles_suppressed": metric(rep["obs"]["bundles_suppressed"],
                                     "trace"),
    }
    # fuse: per-shape counters the tick fusion produced (stable keys —
    # the run is seeded, so the set of nonzero shapes is pinned too).
    for k in sorted(tick):
        if k.startswith("fuse_"):
            m[k] = metric(tick[k], "fuse")
    m.update(_hlo_flat_metrics())

    serve_cell = {
        "kind": "cpu",
        "workload": {**SMALL_LOADGEN, **SERVE_SHAPE, "engine": "flat",
                     "train_ticks": SERVE_TRAIN_TICKS,
                     "wire": cfg.wire_format, "ckpt": cfg.ckpt_format,
                     "hlo_buckets": list(HLO_BUCKETS),
                     "hlo_lanes": SERVE_SHAPE["lanes_per_shard"]},
        "metrics": m,
    }

    steps = max(c.steps, 1)
    lanes_cell = {
        "kind": "cpu",
        "workload": {**SMALL_LOADGEN, **SERVE_SHAPE,
                     "block_k": K, "lane_capacity_runs": cap_runs,
                     "NBT": NBT, "order_capacity": OCAP,
                     "source": "flat-backend tick trace (bit-identical "
                               "streams; lanes-backend re-derivation is "
                               "the ~90s pallas-interpret path — "
                               "perf/serve_lanes_r7.json holds it at "
                               "full scale)"},
        "metrics": {
            "trace_steps": metric(c.steps, "touched-rows"),
            "splits": metric(c.splits, "touched-rows"),
            "hint_misses": metric(c.hint_misses, "touched-rows"),
            "hint_probes": metric(c.hint_probes, "touched-rows"),
            "touched_rows_per_step_flat": metric(
                round(c.unb_touched / steps, 1), "touched-rows"),
            "touched_rows_per_step_blocked": metric(
                round(c.blk_touched / steps, 1), "touched-rows"),
            "touched_rows_ratio": metric(
                round(c.unb_touched / max(c.blk_touched, 1), 2),
                "touched-rows"),
            "pass_traffic_per_step_flat": metric(
                round(c.unb_traffic / steps, 1), "touched-rows"),
            "pass_traffic_per_step_blocked": metric(
                round(c.blk_traffic / steps, 1), "touched-rows"),
            "pass_traffic_ratio": metric(
                round(c.unb_traffic / max(c.blk_traffic, 1), 2),
                "touched-rows"),
        },
    }
    return serve_cell, lanes_cell


def _flow_metrics(rep: dict) -> dict:
    """The ``flow`` family metrics off a loadgen report's flow block:
    span terminal-state census + op-age-at-apply percentiles, ALL exact
    (ages are logical-tick integers — the same-seed determinism that
    pins every other cpu metric pins these).  The audit must be green
    before anything is pinned: a ledger cell recording a leaky run
    would gate the wrong contract."""
    f = rep["flow"]
    assert f["audit_ok"], f["findings"][:4]
    assert f["spans"]["in_flight"] == 0, f
    m = {
        "flow_events": metric(f["flow_events"], "flow"),
        "spans_emitted": metric(f["spans"]["emitted"], "flow"),
        "spans_applied": metric(f["spans"]["applied"], "flow"),
        "spans_rejected": metric(f["spans"]["rejected"], "flow"),
        "spans_in_flight": metric(f["spans"]["in_flight"], "flow"),
        "dup_applies": metric(f["duplicates"], "flow"),
        "applies_device": metric(f["applies"]["device"], "flow"),
        "applies_host": metric(f["applies"]["host"], "flow"),
        "age_p50_ticks": metric(f["ages_ticks"]["p50"], "flow"),
        "age_p99_ticks": metric(f["ages_ticks"]["p99"], "flow"),
        "age_max_ticks": metric(f["ages_ticks"]["max"], "flow"),
    }
    for band, st in f["by_band"].items():
        if st["count"]:
            m[f"age_{band}_p50_ticks"] = metric(st["p50"], "flow")
            m[f"age_{band}_p99_ticks"] = metric(st["p99"], "flow")
    for cls, st in f["by_class"].items():
        if st["count"]:
            key = cls.replace("-", "_")
            m[f"age_{key}_count"] = metric(st["count"], "flow")
            m[f"age_{key}_p50_ticks"] = metric(st["p50"], "flow")
    return m


def cell_flow():
    """The per-op provenance cell (ISSUE 11): the small seeded loadgen
    with FULL flow sampling (``flow_sample_mod=1``) — every emitted
    span tracked end to end, the conservation audit asserted green,
    and the op-age-at-apply distribution pinned in exact logical
    ticks.  This is the before/after latency contract the ROADMAP-7
    pipelined-tick refactor runs against: logical ages must stay
    byte-identical while only wall time moves."""
    from text_crdt_rust_tpu.config import ServeConfig
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    cfg = ServeConfig(engine="flat", flow_sample_mod=1, **SERVE_SHAPE)
    gen = ServeLoadGen(cfg=cfg, **SMALL_LOADGEN)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    return {
        "kind": "cpu",
        "workload": {**SMALL_LOADGEN, **SERVE_SHAPE, "engine": "flat",
                     "flow_sample_mod": 1},
        "metrics": _flow_metrics(rep),
    }


def cell_recovery():
    """Durability cell (ISSUE 16): the pinned post-dispatch crash —
    kill at tick CHAOS_CRASH_TICK with the depth-2 pipeline in flight,
    recover a fresh server by re-executing the journal, resume the
    surviving clients, and require byte-identity to an uncrashed
    same-seed twin plus green crash-boundary conservation audits —
    all asserted BEFORE anything is pinned.

    The byte metrics pin the full-input-log cost model (PERF.md §21):
    ``journal_bytes_per_op`` is floored by the wire txn bytes/op (a
    REC_TXNS body IS the columnar wire frame), and the control-plane
    records (REQUEST/DIGEST/poll trajectory inputs) ride on top — the
    ratio against the wire bill is pinned exactly so any journal-
    format or trajectory-input change shows up as a named diff."""
    from text_crdt_rust_tpu.serve.chaos import run_crash_scenario

    cell = run_crash_scenario(
        "post-dispatch", CHAOS_CRASH_TICK,
        ticks=SMALL_LOADGEN["ticks"] + 3, docs=SMALL_LOADGEN["docs"],
        agents_per_doc=SMALL_LOADGEN["agents_per_doc"],
        events_per_tick=SMALL_LOADGEN["events_per_tick"], seed=SEED,
        fault_rate=SMALL_LOADGEN["fault_rate"], **CHAOS_SHAPE)
    assert cell["identical"], "recovered streams diverged from twin"
    assert cell["converged"] and cell["twin_converged"]
    assert cell["at_recovery_audit"]["audit_ok"], \
        cell["at_recovery_audit"]["findings"]
    assert cell["final_audit"]["audit_ok"], cell["final_audit"]["findings"]
    rec = cell["recover"]
    wire = cell["report"]["wire"]
    jper = cell["journal_bytes_per_op"]
    m = {
        # The replay economy: what recovery re-executed, all logical.
        "journal_records": metric(rec["records"], "recovery"),
        "journal_refusals": metric(rec["refusals"], "recovery"),
        "replayed_ops": metric(rec["ops"], "recovery"),
        "replayed_txns": metric(rec["txns_replayed"], "recovery"),
        "replayed_locals": metric(rec["locals_replayed"], "recovery"),
        "replayed_frames": metric(rec["frames_replayed"], "recovery"),
        "replayed_polls": metric(rec["polls_replayed"], "recovery"),
        "ticks_to_recover": metric(rec["ticks"], "recovery"),
        "docs_readmitted": metric(rec["docs"], "recovery"),
        # The journal byte bill at the crash point (shipped fsync
        # cadence = every tick), against the wire bill of the full run.
        "journal_bytes": metric(cell["journal_bytes"], "recovery"),
        "journal_ops": metric(cell["journal_ops"], "recovery"),
        "journal_bytes_per_op": metric(jper, "recovery"),
        "wire_txn_bytes_per_op": metric(wire["bytes_per_op"], "wire"),
        "journal_vs_wire_txn_x": metric(
            round(jper / wire["bytes_per_op"], 3), "recovery"),
    }
    return {
        "kind": "cpu",
        "workload": {**SMALL_LOADGEN, **CHAOS_SHAPE,
                     "ticks": SMALL_LOADGEN["ticks"] + 3,
                     "phase": "post-dispatch",
                     "crash_tick": CHAOS_CRASH_TICK,
                     "fsync_ticks": 1},
        "metrics": m,
    }


def cell_flash_crowd():
    """Flash-crowd cell (ISSUE 16 satellite): from FLASH_TICK on, 90%
    of every tick's events slam doc FLASH_DOC while the lanes are far
    too small for it — the hot doc must ride the overflow-degrade path
    (host oracle, counted) and thrash eviction/restore, and the run
    must still converge bit-identically.  Pinned so the degrade and
    thrash economy of the hot-doc pathology is a named diff, not a
    flaky incident."""
    from text_crdt_rust_tpu.config import ServeConfig
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    cfg = ServeConfig(engine="flat", lane_capacity=128,
                      order_capacity=256, **FLASH_SHAPE)
    gen = ServeLoadGen(cfg=cfg, **{**SMALL_LOADGEN, "ticks": 10,
                                   "events_per_tick": 24},
                       flash_crowd=(FLASH_TICK, FLASH_DOC))
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    c = gen.server.counters
    srv = rep["server"]
    assert c.get("lane_overflow_degraded") > 0, \
        "flash shape never overflowed — the cell tests nothing"
    hot = gen.worlds[FLASH_DOC]
    m = {
        "item_ops_applied": metric(rep["item_ops_applied"], "steps"),
        "hot_doc_chars": metric(len(hot.twin), "steps"),
        "lane_overflow_degraded": metric(
            c.get("lane_overflow_degraded"), "admission"),
        "evictions": metric(srv.get("evictions", 0), "ckpt"),
        "restores": metric(srv.get("restores", 0), "ckpt"),
        "ckpt_bytes_written": metric(srv.get("ckpt_bytes_written", 0),
                                     "ckpt"),
        "rejected_submissions": metric(rep["rejected_submissions"],
                                       "admission"),
        "wire_txn_bytes": metric(rep["wire"]["txn_bytes"], "wire"),
    }
    return {
        "kind": "cpu",
        "workload": {**SMALL_LOADGEN, **FLASH_SHAPE, "ticks": 10,
                     "events_per_tick": 24, "lane_capacity": 128,
                     "order_capacity": 256,
                     "flash_crowd": f"{FLASH_TICK}:{FLASH_DOC}"},
        "metrics": m,
    }


def cell_fused_trace():
    """Generalized step fusion over a pinned real-trace prefix compiled
    at the serve lmax — the ISSUE-6 step economy as exact counters."""
    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.utils.testdata import (
        flatten_patches,
        load_testing_data,
        trace_path,
    )

    patches = flatten_patches(
        load_testing_data(trace_path(FUSED_TRACE)))[:FUSED_PATCHES]
    ops, _ = B.compile_local_patches(patches, lmax=FUSED_LMAX, dmax=None)
    _fused, fs = B.fuse_steps(ops, fuse_w=FUSED_W)
    m = {
        "steps_prefuse": metric(fs.steps_in, "fuse"),
        "steps_fused": metric(fs.steps_out, "fuse"),
        "rows_saved": metric(fs.rows_saved, "fuse"),
        "reduction_x": metric(round(fs.reduction_x, 3), "fuse"),
    }
    for shape, n in sorted(fs.fused.items()):
        m[f"fuse_{shape}"] = metric(n, "fuse")
    return {
        "kind": "cpu",
        "workload": {"trace": FUSED_TRACE, "patches": FUSED_PATCHES,
                     "lmax": FUSED_LMAX, "fuse_w": FUSED_W},
        "metrics": m,
    }


def cell_sp():
    """The sequence-parallel engine's static ICI cost model at a tiny
    pinned shape: collectives/step by kind off the compiled HLO (scan
    body emitted once -> textual occurrences = per-step cost)."""
    import jax.numpy as jnp
    import numpy as np

    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.parallel import make_mesh
    from text_crdt_rust_tpu.parallel.sp_apply import SpDoc
    from text_crdt_rust_tpu.utils.testdata import (
        flatten_patches,
        load_testing_data,
        trace_path,
    )

    patches = flatten_patches(
        load_testing_data(trace_path("automerge-paper")))[:SP_PATCHES]
    merged = B.merge_patches(patches)
    lmax = max([len(p.ins_content) for p in merged] + [1])
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    mesh = make_mesh(n_devices=8, dp=1, sp=8)
    sdoc = SpDoc(mesh, shard_rows=SP_SHARD_ROWS, order_rows=64,
                 auto_reshard=True)
    cols = tuple(
        jnp.asarray(np.asarray(col, dtype=np.uint32).view(np.int32))
        for col in (ops.kind, ops.pos, ops.del_len, ops.del_target,
                    ops.origin_left, ops.origin_right, ops.rank,
                    ops.ins_len, ops.ins_order_start))
    lowered = sdoc._replay.lower(sdoc.ordp, sdoc.lenp, sdoc.rows,
                                 sdoc.oll, sdoc.orl, sdoc.rkl, *cols)
    cost = _hlo_cost(lowered)
    m = {
        "steps": metric(ops.num_steps, "steps"),
        "collectives_per_step": metric(cost["collectives"], "hlo"),
        "hlo_flops": metric(cost["flops"], "hlo", tol=HLO_TOL),
        "hlo_bytes": metric(cost["bytes"], "hlo", tol=HLO_TOL),
    }
    for kind, n in sorted(cost["by_kind"].items()):
        m[f"collectives_{kind.replace('-', '_')}"] = metric(n, "hlo")
    return {
        "kind": "cpu",
        "workload": {"trace": "automerge-paper", "patches": SP_PATCHES,
                     "sp": 8, "shard_rows": SP_SHARD_ROWS,
                     "order_rows": 64},
        "metrics": m,
    }


def cell_serve_device():
    """Silicon cell (perf/when_up_r11.sh): the same small loadgen on
    the DEFAULT jax backend — per-bucket device-step wall histograms
    plus the real-HLO flat-kernel costs.  Wall metrics carry wide bands
    (they gate nothing on CPU; the cell is the committed record of what
    the chip measured)."""
    import jax

    from text_crdt_rust_tpu.config import ServeConfig
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    platform = jax.devices()[0].platform
    cfg = ServeConfig(engine="flat", **SERVE_SHAPE)
    gen = ServeLoadGen(cfg=cfg, **SMALL_LOADGEN)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    srv = rep["server"]
    m = {}
    for key in sorted(srv):
        if key.startswith("device_step_wall_ms_b") and key.rsplit(
                "_", 1)[-1] in ("mean", "p50", "p99"):
            m[key] = metric(srv[key], "wall", tol=WALL_TOL)
    m["tick_wall_ms_p50"] = metric(srv.get("tick_wall_ms_p50", 0.0),
                                   "wall", tol=WALL_TOL)
    m["tick_wall_ms_p99"] = metric(srv.get("tick_wall_ms_p99", 0.0),
                                   "wall", tol=WALL_TOL)
    for name, entry in _hlo_flat_metrics(platform).items():
        m[f"device_{name}"] = entry
    return {
        "kind": "device",
        "workload": {**SMALL_LOADGEN, **SERVE_SHAPE, "engine": "flat",
                     "platform": platform},
        "metrics": m,
    }


def cell_flow_device():
    """Silicon variant of the ``flow`` cell (perf/when_up_r11.sh): the
    SAME full-sampling loadgen on the default jax backend.  Because op
    ages are logical-tick integers, the chip must reproduce the cpu
    cell's numbers EXACTLY — this cell is the cross-backend proof that
    per-op latency accounting is device-independent — plus the run's
    wall clock as a banded informational metric."""
    import time

    import jax

    from text_crdt_rust_tpu.config import ServeConfig
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    platform = jax.devices()[0].platform
    cfg = ServeConfig(engine="flat", flow_sample_mod=1, **SERVE_SHAPE)
    gen = ServeLoadGen(cfg=cfg, **SMALL_LOADGEN)
    t0 = time.perf_counter()
    rep = gen.run()
    wall = time.perf_counter() - t0
    assert rep["converged"], rep["mismatches"][:4]
    m = _flow_metrics(rep)
    m["run_wall_s"] = metric(round(wall, 3), "wall", tol=WALL_TOL)
    return {
        "kind": "device",
        "workload": {**SMALL_LOADGEN, **SERVE_SHAPE, "engine": "flat",
                     "flow_sample_mod": 1, "platform": platform},
        "metrics": m,
    }


def derive_cells(names=None) -> dict:
    """Derive the named cpu cells (all of them by default).  ``serve``
    and ``serve-lanes`` share one loadgen run, so requesting either
    derives both internally."""
    names = list(names) if names is not None else list(CPU_CELLS)
    unknown = [n for n in names if n not in CPU_CELLS]
    if unknown:
        raise ValueError(f"unknown ledger cells {unknown}; cpu cells "
                         f"are {CPU_CELLS}")
    out = {}
    if "serve" in names or "serve-lanes" in names:
        serve_cell, lanes_cell = cell_serve_pair()
        if "serve" in names:
            out["serve"] = serve_cell
        if "serve-lanes" in names:
            out["serve-lanes"] = lanes_cell
    if "fused-trace" in names:
        out["fused-trace"] = cell_fused_trace()
    if "sp" in names:
        out["sp"] = cell_sp()
    if "flow" in names:
        out["flow"] = cell_flow()
    if "recovery" in names:
        out["recovery"] = cell_recovery()
    if "flash-crowd" in names:
        out["flash-crowd"] = cell_flash_crowd()
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=LEDGER_PATH)
    ap.add_argument("--cells", default=None,
                    help="comma-separated cell subset (default: all "
                         "cpu cells)")
    ap.add_argument("--device", action="store_true",
                    help="derive the SILICON cells on the default jax "
                         "backend and merge them into --out, keeping "
                         "the committed cpu cells")
    a = ap.parse_args()

    import jax

    if a.device:
        cells = {"serve-device": cell_serve_device(),
                 "flow-device": cell_flow_device()}
        with open(a.out) as f:
            ledger = json.load(f)
        ledger["cells"].update(cells)
        ledger.setdefault("recorded", {})["device"] = {
            "jax": jax.__version__,
            "platform": jax.devices()[0].platform,
        }
    else:
        _force_cpu()
        want = a.cells.split(",") if a.cells else None
        cells = derive_cells(want)
        prior = {}
        if os.path.exists(a.out):
            with open(a.out) as f:
                prior = json.load(f)
        # A cpu re-record NEVER erases silicon work: prior device cells
        # (and their provenance) always survive.  A full re-record
        # supersedes every cpu cell (stale renamed cells drop); a
        # --cells partial keeps the cpu cells it didn't re-derive.
        merged = {n: c for n, c in prior.get("cells", {}).items()
                  if c.get("kind") == "device" or (want and n not in
                                                   cells)}
        merged.update(cells)
        recorded = dict(prior.get("recorded", {}))
        recorded.update({
            "probe": "perf/cost_ledger_probe.py",
            "jax": jax.__version__,
            "note": "cpu cells are exact logical counters (same-"
                    "seed deterministic, PERF.md §14) except hlo "
                    "metrics, which carry relative tolerance "
                    "bands; re-derive with bench.py --check-ledger",
        })
        ledger = {
            "schema_version": LEDGER_SCHEMA_VERSION,
            "recorded": recorded,
            "cells": merged,
        }
    validate_ledger(ledger)
    with open(a.out, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True)
        f.write("\n")
    n_metrics = sum(len(c["metrics"]) for c in cells.values())
    print(f"recorded {len(cells)} cell(s) / {n_metrics} metrics "
          f"into {a.out}", file=sys.stderr)
    print(json.dumps({"cells": sorted(ledger["cells"]),
                      "metrics": n_metrics}))


if __name__ == "__main__":
    main()

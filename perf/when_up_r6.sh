#!/bin/bash
# Round-6 recovery watcher (ISSUE 2): the blocked streaming-lanes
# engines (configs 5/5r) and the split-tail origin-right repair landed
# CPU-verified only — the tunnel was down for the whole PR.  On
# recovery: compile pins first (the blocked kernels' NB-way select
# chains and the hint-table cond paths have never met Mosaic — if they
# are a compiler problem, this is where it shows, loudly and bounded),
# then re-record ONLY the rows this PR's engines changed (5, 5r) plus
# the northstar sanity row, then the full-suite resume fills any gaps.
# Targets (VERDICT next #2 / ISSUE 2): config 5r >= 4x its recorded
# x10.4; perf/blocked_lanes_sim.py predicts the blocked step's touched
# rows at ~15x fewer (traffic model ~5x — the chip decides).
# Safe to re-run; appends to perf/when_up_r6.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r6 watcher)" >> perf/when_up_r6.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r6)" >> perf/when_up_r6.log
  sleep 120
done
# Compile pins: existing geometries + a real-shape blocked-lanes smoke
# (2048 lanes x growing caps is exactly what cfg 5/5r will launch).
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r6.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r6.log
timeout 1800 python bench.py --config 5 --smoke --no-probe \
  >> perf/when_up_r6.log 2>&1 \
  || echo "cfg5 smoke FAILED rc=$?" >> perf/when_up_r6.log
# Drop the superseded 5/5r rows, then re-record them + northstar.
python - <<'EOF'
import json, os
rows = json.load(open("BENCH_ALL.json"))
keep = [r for r in rows if r.get("cfg_key") not in ("5", "5r")]
if len(keep) != len(rows):
    with open("BENCH_ALL.json.tmp", "w") as f:
        json.dump(keep, f, indent=1)
    os.replace("BENCH_ALL.json.tmp", "BENCH_ALL.json")
EOF
timeout 7200 python bench.py --config all --resume >> perf/bench_all_r6.log 2>&1 \
  || echo "bench exited nonzero; rows up to the failure are persisted" \
       >> perf/bench_all_r6.log
echo "$(date -u +%H:%M:%S) r6 re-record done" >> perf/when_up_r6.log

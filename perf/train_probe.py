"""Tick-train probe (ISSUE 20 acceptance): T ticks as one device
``lax.scan`` program vs the serial one-dispatch-per-tick loop, at the
200-doc faulted acceptance shape.

Three arms of the SAME seeded loadgen (the ``device_prefill_probe``
pattern): train depth {1, 2, 4}, all at pipeline depth 2 with
device-resident prefill.  Every arm's logical stream is sha256-hashed
and ALL THREE must be identical — train length is a wall-clock knob
only.  Per arm the probe records:

- **dispatch economy** (the ledger-gated counters): device dispatches,
  dispatches per tick, and ``dispatch_cut_x`` — the serial-equivalent
  dispatch count over the actual one.  The committed depth-4 cut must
  be >= 3x (theoretical ceiling at depth 4 is 8/2 = 4x: T step
  dispatches + T scatter dispatches collapse to 1 train scan + 1
  concatenated scatter; partial flushes at lane residency boundaries
  eat the rest).
- **loop wall** (min of ``reps``): no train depth may regress depth 1
  by > 5%.  On the CPU tier-1 box each dispatch is a cheap Python
  call, so the honest readout is parity-within-noise; the silicon
  re-record (perf/when_up_r16.sh) is where T-for-one dispatch
  amortization actually pays.
- **compile economy**: distinct (T-bucket, S-bucket) train programs
  compiled — the power-of-two pad series must keep this bounded (the
  compile set is ADDITIVE: train programs + scatter programs, because
  the concatenated scatter stays a separate dispatch).

Writes ``perf/train_r17.json``.

Run: python perf/train_probe.py [--smoke] [--reps N] [--out P]
"""
import argparse
import hashlib
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

if "--device" not in sys.argv:
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # in-process import after backend init (the tier-1 smoke)

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402

WALL_REGRESSION_PCT = 5.0
DISPATCH_CUT_FLOOR_X = 3.0
TRAIN_DEPTHS = (4, 2, 1)


def run_one(smoke: bool, *, train_ticks: int, seed: int = 7):
    """One seeded loadgen run; returns (report, loop_wall_s, sha256)."""
    docs, ticks, events = (24, 12, 16) if smoke else (200, 60, 48)
    cfg = ServeConfig(engine="flat", num_shards=4, lanes_per_shard=16,
                      pipeline_ticks=2, train_ticks=train_ticks,
                      flow_sample_mod=16, trace_keep=True)
    gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                       events_per_tick=events, zipf_alpha=1.1,
                       fault_rate=0.10, local_prob=0.25, seed=seed,
                       cfg=cfg)
    t0 = time.perf_counter()
    rep = gen.run()
    wall = time.perf_counter() - t0
    assert rep["converged"], rep["mismatches"][:4]
    sha = hashlib.sha256(
        gen.server.tracer.logical_bytes()).hexdigest()
    return rep, wall, sha


def _arm_row(rep: dict) -> dict:
    tr = rep["train"]
    return {
        "train_ticks": tr["ticks"],
        "loop_wall_s": rep["device_ticks_wall_s"],
        "device_dispatches": tr["device_dispatches"],
        "dispatches_per_tick": tr["dispatches_per_tick"],
        "dispatch_cut_x": tr["dispatch_cut_x"],
        "train_len": tr["train_len"],
        "train_compiles": tr["train_compiles"],
        "device_steps": rep["server"].get("device_steps", 0),
        "device_compiles": rep["server"].get("device_compiles", 0),
        "evictions": rep["server"].get("evictions", 0),
        "flow_audit_ok": rep["flow"]["audit_ok"],
        "flow_age_p50": rep["flow"]["ages_ticks"]["p50"],
    }


def _warm_compiles(smoke: bool) -> None:
    """Warm every jit cache untimed BEFORE any timed arm: the per-tick
    step/scatter programs via one smoke run per depth, then EVERY
    (T-bucket, S-bucket) train program a full-scale run can hit — a
    partial flush at an eviction boundary can dispatch any (T, S) pair,
    and one mid-arm train compile (~0.5 s x up to 12 distinct programs)
    would bill compiler order as dispatch cost (the first cut of this
    probe measured exactly that as a fake 12% wall regression)."""
    import numpy as np

    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.ops import flat as F
    from text_crdt_rust_tpu.serve.batcher import FlatLaneBackend

    for t in TRAIN_DEPTHS:
        run_one(True, train_ticks=t)
    cfg = ServeConfig()
    backend = FlatLaneBackend(lanes=cfg.lanes_per_shard,
                              capacity=cfg.lane_capacity,
                              order_capacity=cfg.order_capacity,
                              lmax=cfg.lmax)
    lanes = cfg.lanes_per_shard
    for s_bkt in cfg.step_buckets:
        tick = B.stack_ops(
            [B.pad_ops(B.empty_ops(cfg.lmax), s_bkt)] * lanes)
        for t_bkt in (1, 2, 4):
            train = B.stack_ticks([tick] * t_bkt)
            F.apply_train(backend.docs, train)
    bucket_cap = cfg.step_buckets[-1] * cfg.lmax
    L = B.PREFILL_BUCKET_BASE
    while L <= bucket_cap:
        pad = np.full((lanes, L), B.PREFILL_PAD, np.uint32)
        zero = np.zeros_like(pad)
        delta = B.PrefillDelta(pad, zero, zero, pad, zero, pad, zero,
                               bucket=L)
        F.apply_prefill_delta(backend.docs, delta)
        L *= 4


def run_matrix(smoke: bool = False, reps: int = 2) -> dict:
    _warm_compiles(smoke)
    arms = {}
    hashes = {}
    walls = {f"train{t}": [] for t in TRAIN_DEPTHS}
    best = {}
    # Interleave the reps (arm order inside each rep round) so shared-
    # box drift lands evenly across arms; min-of-reps per arm.
    for _ in range(reps):
        for t in TRAIN_DEPTHS:
            key = f"train{t}"
            rep, wall, h = run_one(smoke, train_ticks=t)
            assert hashes.setdefault(key, h) == h, \
                "same-seed arm reruns diverged"
            walls[key].append(rep["device_ticks_wall_s"])
            if (key not in best or rep["device_ticks_wall_s"]
                    < best[key]["device_ticks_wall_s"]):
                best[key] = rep
    for key, rep in best.items():
        arms[key] = _arm_row(rep)
        arms[key]["loop_walls_s"] = walls[key]

    identical = len(set(hashes.values())) == 1
    t4, t2, t1 = arms["train4"], arms["train2"], arms["train1"]
    wall_delta_pct = {
        "train4": round((t4["loop_wall_s"] - t1["loop_wall_s"])
                        / t1["loop_wall_s"] * 100.0, 2),
        "train2": round((t2["loop_wall_s"] - t1["loop_wall_s"])
                        / t1["loop_wall_s"] * 100.0, 2),
    }
    logical_counters_identical = all(
        a["device_steps"] == t1["device_steps"]
        and a["device_compiles"] == t1["device_compiles"]
        and a["evictions"] == t1["evictions"]
        and a["flow_age_p50"] == t1["flow_age_p50"]
        and a["flow_audit_ok"]
        for a in arms.values())

    out = {
        "probe": "train",
        "smoke": smoke,
        "workload": {
            "docs": 24 if smoke else 200, "seed": 7, "engine": "flat",
            "fault_rate": 0.10, "reps_per_arm": reps,
            "basis": "min loop wall (device_ticks_wall_s) per arm; "
                     "logical metrics from the min-wall rep",
        },
        "arms": arms,
        "stream_sha256": hashes,
        "acceptance": {
            "dispatch_cut_floor_x": DISPATCH_CUT_FLOOR_X,
            "wall_regression_bar_pct": WALL_REGRESSION_PCT,
            "streams_sha256_identical": identical,
            "logical_counters_identical": logical_counters_identical,
            "dispatch_cut_x": {"train4": t4["dispatch_cut_x"],
                               "train2": t2["dispatch_cut_x"],
                               "train1": t1["dispatch_cut_x"]},
            "wall_delta_pct": wall_delta_pct,
            # Smoke walls are sub-second shared-box noise: the wall bar
            # gates only the full-scale (committed) run, like the
            # device-prefill probe's smoke tier.  Smoke runs are also
            # too short to amortize partial flushes, so the cut floor
            # relaxes to "deeper trains strictly cut dispatches".
            "pass": bool(
                identical and logical_counters_identical
                and t1["dispatch_cut_x"] == 1.0
                and t4["dispatch_cut_x"] > t2["dispatch_cut_x"] > 1.0
                and (smoke
                     or t4["dispatch_cut_x"] >= DISPATCH_CUT_FLOOR_X)
                and (smoke or max(wall_delta_pct.values())
                     <= WALL_REGRESSION_PCT)),
        },
        "note": "CPU run (tier-1 harness): a dispatch here is a cheap "
                "Python-to-XLA call, so the wall gate is parity-within-"
                "noise (<=5%); the dispatch cut is the structural win "
                "and the silicon re-record (when_up_r16.sh) is where "
                "T-for-one launch amortization shows up as wall. "
                "Logical metrics are seed-deterministic and platform-"
                "independent; depth-4 cut < 4x ceiling because lane "
                "residency boundaries (evict, upload, rank-table "
                "growth on an active lane) force partial flushes.",
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--device", action="store_true",
                    help="run on the default jax backend instead of "
                         "forcing CPU (perf/when_up_r16.sh; write to a "
                         "separate --out so the committed CPU record "
                         "stays the tier-1 reference)")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default="perf/train_r17.json")
    a = ap.parse_args()
    out = run_matrix(smoke=a.smoke, reps=a.reps)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out, indent=1))
    if not out["acceptance"]["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

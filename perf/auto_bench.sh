#!/bin/bash
# Probe until the chip answers, then run the full bench table + kevin 5M.
cd /root/repo
for i in $(seq 1 200); do
  timeout 90 python -c "
import jax, jax.numpy as jnp
y = (jnp.ones((64,64))@jnp.ones((64,64))).sum()
print('CHIP_OK', float(y))" 2>/dev/null | grep -q CHIP_OK && break
  sleep 90
  [ $i -eq 200 ] && exit 1
done
echo "chip recovered at $(date)" > perf/auto_bench.log
python bench.py --config all --reps 8 --out BENCH_ALL.json >> perf/auto_bench.log 2>&1
echo "BENCH_ALL done rc=$? at $(date)" >> perf/auto_bench.log
python bench.py --config kevin --kevin-n 5000000 --batch 64 --reps 1 >> perf/kevin5m.log 2>&1
echo "kevin5m done rc=$? at $(date)" >> perf/auto_bench.log

#!/bin/bash
# Round-9 recovery watcher (ISSUE 6 / ROADMAP #4): generalized fused
# multi-row steps are CPU-proven (perf/fused_traces_r9.json: automerge
# 35.0x / rustcode 3.5x / sveltecomponent 4.2x event-step cut, all four
# fused-splice surfaces bit-identical) — this arms the silicon
# re-record.  Supersedes when_up_r8.sh and keeps its gate chain:
# matmul tunnel probe -> compile pin -> fused kevin device smoke (the
# W-row splice + rows_per_step SMEM column on real Mosaic) -> kevin
# full 5M -> the remaining rows, now with the fused defaults live:
# northstar records the fuse_steps'd merged stream (--fuse-w 8 default,
# steps_fused/fuse_shapes in the row payload) and serve/serve-lanes
# record fused ticks end-to-end (tick_summary fused-step counters).
# Each config re-records through `--merge-rows` (single config ->
# BENCH_ALL.json row replacement; no hand-editing, no suite resume).
# Safe to re-run; appends to perf/when_up_r9.log.
set -u
cd /root/repo
while true; do
  if timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(np.asarray(x @ x)[0,0]) == 128.0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is back (r9 watcher)" >> perf/when_up_r9.log
    break
  fi
  echo "$(date -u +%H:%M:%S) still down (r9)" >> perf/when_up_r9.log
  sleep 120
done
timeout 2400 python perf/compile_pin.py >> perf/compile_pin_r9.log 2>&1 \
  || echo "PIN FAILED/TIMED OUT rc=$? - investigate before trusting bench" \
       >> perf/compile_pin_r9.log
# Fused-kernel device smoke first: a tiny fused kevin (2048 prepends,
# W=8) proves the W-row splice compiles on real Mosaic before
# committing to the 40-min full run.
timeout 1800 python bench.py --config kevin --smoke --no-probe \
  >> perf/when_up_r9.log 2>&1 \
  || { echo "fused kevin device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r9.log; exit 1; }
# Second gate: a fused serve-lanes loadgen smoke — the blocked mixed
# kernel's fused splice + the serve stack's fused ticks on device.
timeout 1800 python -m text_crdt_rust_tpu.serve.loadgen --device \
  --docs 24 --ticks 10 --engine rle-lanes-mixed \
  >> perf/when_up_r9.log 2>&1 \
  || { echo "fused serve-lanes device smoke FAILED rc=$? - NOT re-recording" \
         >> perf/when_up_r9.log; exit 1; }
# Headline: kevin at full 5M, fused W=64 (rle-hbm-fused row).
timeout 7200 python bench.py --config kevin --merge-rows --no-probe \
  >> perf/bench_kevin_r9.log 2>&1 \
  || echo "kevin re-record FAILED rc=$?" >> perf/when_up_r9.log
# Remaining rows, most verdict-critical first; northstar + serve rows
# pick up the fused defaults (steps_fused / fuse_shapes / tick_summary
# counters land in the payloads automatically).
for cfg in northstar 4 5r 5 serve serve-lanes sp; do
  timeout 7200 python bench.py --config "$cfg" --merge-rows --no-probe \
    >> "perf/bench_cfg${cfg}_r9.log" 2>&1 \
    || echo "config $cfg re-record FAILED rc=$?" >> perf/when_up_r9.log
done
echo "$(date -u +%H:%M:%S) r9 re-record done" >> perf/when_up_r9.log

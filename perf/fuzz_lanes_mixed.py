"""Long-running differential fuzz: rle_lanes_mixed vs the oracle.

Loops over seeds, each round building a batch of divergent lanes that
mix the hard remote shapes — multi-peer merges, concurrent storms with
deletes (make_storm del_prob), and causal-buffer-reordered arrivals —
and asserting per-lane signed-char equality with the oracle.  Failures
print the seed and stop; run under nohup during idle time:

    python perf/fuzz_lanes_mixed.py [--rounds N] [--start-seed S]
"""
import argparse
import random
import sys
import time

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM
from text_crdt_rust_tpu.parallel.causal import CausalBuffer
from text_crdt_rust_tpu.utils.randedit import make_storm, random_patches


def peer(rng, n, agent):
    doc = ListCRDT()
    a = doc.get_or_create_agent_id(agent)
    patches, _ = random_patches(rng, n)
    for p in patches:
        if p.del_len:
            doc.local_delete(a, p.pos, p.del_len)
        if p.ins_content:
            doc.local_insert(a, p.pos, p.ins_content)
    return doc


def lane_stream(rng, seed):
    """One lane's txn stream: a random hard shape."""
    shape = rng.randrange(3)
    if shape == 0:  # multi-peer merge, shuffled through the buffer
        txns = []
        for name in ("ann", "bob", "cyd")[: 2 + rng.randrange(2)]:
            txns.extend(export_txns_since(
                peer(rng, 10 + rng.randrange(25), name), 0))
        rng.shuffle(txns)
        buf = CausalBuffer()
        released = buf.add_all(txns)
        assert buf.pending == 0
        return released
    if shape == 1:  # concurrent storm with cross-peer deletes
        txns, _ = make_storm(2 + rng.randrange(3), 3 + rng.randrange(5),
                             1 + rng.randrange(3), seed=seed,
                             del_prob=0.25 + rng.random() * 0.3)
        return txns
    # interleaved independent peers (different causal order per lane)
    streams = [export_txns_since(peer(rng, 8 + rng.randrange(15), n), 0)
               for n in ("kim", "lou")]
    out = []
    queues = [list(s) for s in streams]
    while any(queues):
        live = [q for q in queues if q]
        out.append(rng.choice(live).pop(0))
    return out


def one_round(seed: int, layouts=("flat", "blocked")) -> int:
    """One fuzz round: every requested layout must match the oracle AND
    (when both run) each other bit-identically — the ISSUE-2 blocked /
    un-blocked differential ride-along."""
    rng = random.Random(seed)
    lanes = 3 + rng.randrange(4)
    lane_txns = [lane_stream(rng, seed * 100 + k) for k in range(lanes)]
    opses = []
    for txns in lane_txns:
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops, _ = B.compile_remote_txns(txns, table, lmax=6, dmax=None)
        opses.append(ops)
    stacked = B.stack_ops(opses)
    results = {}
    if "flat" in layouts:
        results["flat"] = RLM.replay_lanes_mixed(
            stacked, capacity=1024, chunk=32, interpret=True)
    if "blocked" in layouts:
        results["blocked"] = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=1024, block_k=64, chunk=32,
            interpret=True)
    for r in results.values():
        r.check()
    n_ops = 0
    for d, txns in enumerate(lane_txns):
        oracle = ListCRDT()
        for t in txns:
            oracle.apply_remote_txn(t)
        want = [(-1 if oracle.deleted[i] else 1)
                * (int(oracle.order[i]) + 1) for i in range(oracle.n)]
        for name, res in results.items():
            got = RL.expand_lane(res, d).tolist()
            assert got == want, f"seed {seed} lane {d} {name} DIVERGED"
        n_ops += oracle.n
    if len(results) == 2:
        assert np.array_equal(np.asarray(results["flat"].ol),
                              np.asarray(results["blocked"].ol)) \
            and np.array_equal(np.asarray(results["flat"].orr),
                               np.asarray(results["blocked"].orr)), \
            f"seed {seed}: blocked origins diverged from flat"
    return n_ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=200)
    ap.add_argument("--start-seed", type=int, default=10_000)
    ap.add_argument("--layout", default="both",
                    choices=("both", "flat", "blocked"))
    args = ap.parse_args()
    layouts = (("flat", "blocked") if args.layout == "both"
               else (args.layout,))
    t0 = time.time()
    total = 0
    for k in range(args.rounds):
        seed = args.start_seed + k
        total += one_round(seed, layouts)
        if (k + 1) % 10 == 0:
            print(f"{k + 1}/{args.rounds} rounds, {total} chars checked, "
                  f"{time.time() - t0:.0f}s", flush=True)
    print(f"fuzz OK: {args.rounds} rounds, {total} chars, "
          f"{time.time() - t0:.0f}s", flush=True)


if __name__ == "__main__":
    main()
